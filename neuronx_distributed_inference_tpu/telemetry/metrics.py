"""Host-side serving metrics: counters, gauges, fixed-bucket histograms.

The operational layer the reference runtime never shipped (NxDI exposes no
runtime metrics at all): a process-local registry in the spirit of
``prometheus_client`` but with zero dependencies and a hard design
constraint — **recording never talks to the device**. Every instrument is a
plain Python float/int update on the host; values arrive from fetches the
runtime already performs (the batched ``jax.device_get`` per step), so
enabling telemetry adds no host↔device round trips. tpulint rule TPU107
statically proves no recording call is reachable from a jit-traced body
(a metric recorded at trace time would record once and lie forever — the
same failure mode as TPU103's ``time.time()`` under trace).

Exposition:
- :meth:`MetricsRegistry.prometheus_text` — Prometheus text format 0.0.4
  (scrape it from any HTTP handler, or dump to a file).
- :meth:`MetricsRegistry.snapshot` — a JSON-able dict
  (``--metrics-out`` in inference_demo/bench; pretty-printed by
  ``scripts/metrics_report.py``).

Histograms use FIXED bucket bounds chosen at registration (cumulative
``le`` semantics like Prometheus) so observation cost is a bisect + two
adds — no per-observation allocation, no quantile sketch on the hot path.
Exact ``sum``/``count`` are kept so tests can pin conservation laws
(e.g. the speculation acceptance histogram sums to committed tokens).

Thread safety (the CONC603 contract, docs/STATIC_ANALYSIS.md): with the
thread-per-replica router (``TpuConfig.router_threading``) every replica's
step thread records into ONE shared registry, so the instrument mutators are
the atomicity boundary — ``inc``/``set``/``observe`` take a per-instrument
lock (``+=`` on a Python float is a read-modify-write across bytecodes, NOT
atomic under the GIL), ``_Family.child`` mints children under a per-family
lock (two threads asking for the same new label must get the SAME child, not
two — the check-then-act race), and exposition copies each family's child
table under that same family lock before iterating (a scrape thread walking
``children`` while a worker mints a new label would otherwise die
mid-iteration). Call sites must never touch
``.value``/``.sum``/``.count``/bucket internals directly — the concurrency
audit (CONC603) proves that statically.
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

#: the Content-Type the Prometheus text exposition is served under
#: (telemetry/ops_server.py /metrics route)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# default latency bounds (milliseconds): spans admission→TTFT on one chip to
# multi-second queue waits under overload
LATENCY_MS_BUCKETS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)
# speculation acceptance length (tokens per round, 1..k); k <= 16 in practice
ACCEPT_LEN_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)
# adaptive draft lengths (spec-ragged policy choices, snapped powers of two)
DRAFT_LEN_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0)
# per-request acceptance-rate EWMA (spec-ragged adaptive-draft signal, 0..1)
SPEC_EWMA_BUCKETS = (0.1, 0.25, 0.4, 0.5, 0.6, 0.75, 0.9, 1.0)
# prefill chunks consumed per request before the first token
CHUNK_COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
# ragged mixed-step composition (rows / slots per dispatch): spans one
# decode row up to a fully-packed total-token bucket
MIXED_STEP_BUCKETS = (
    0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0,
)
# multi-replica router occupancy spread (max - min live rows across alive
# replicas, observed once per router step): 0 == perfectly balanced
ROUTER_SPREAD_BUCKETS = (
    0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 64.0,
)


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...], extra: str = "") -> str:
    parts = [f'{n}="{v}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotone counter. ``inc`` is the ONLY mutator — and the atomic
    section: replica step threads share instruments, and a bare ``+=``
    loses increments under interleaving."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increments must be >= 0, got {n}")
        with self._lock:
            self.value += n


class Gauge:
    """Last-value gauge (pool occupancy, bytes free, batch fill)."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Fixed-bound cumulative histogram with exact sum/count.

    ``bounds`` are the finite upper bounds; an implicit +Inf bucket catches
    the tail. ``counts[i]`` is NON-cumulative per bucket (cumulated only at
    exposition) so ``observe`` stays O(log n_buckets).

    ``observe`` updates bucket + sum + count as ONE atomic section: an
    unlocked interleaving could commit a bucket increment without its sum
    (or vice versa) and break the exact-conservation pins the tests rely on.
    """

    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds: Sequence[float]):
        b = tuple(float(x) for x in bounds)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"histogram bounds must be strictly increasing: {b}")
        self.bounds = b
        self.counts = [0] * (len(b) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.counts[bisect.bisect_left(self.bounds, float(v))] += 1
            self.sum += float(v)
            self.count += 1

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-resolution quantile estimate (upper bound of the bucket the
        q-th observation falls in; +Inf tail reports the largest finite
        bound). None when empty. Coarse by design — exact percentiles come
        from traces, not histograms (utils/benchmark + bench serving rows
        use per-request traces)."""
        if self.count == 0:
            return None
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank and c:
                return self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
        return self.bounds[-1]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One registered metric name: help text, kind, label names, children
    keyed by label-value tuples. Unlabelled metrics have a single child at
    the empty key."""

    __slots__ = ("name", "kind", "help", "label_names", "buckets", "children",
                 "_lock")

    def __init__(self, name, kind, help_text, label_names, buckets=None):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets) if buckets else None
        self.children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def child(self, label_values: Tuple[str, ...]):
        # fast path: an existing child is immutable membership (children are
        # never removed), so the lock-free read is safe; the MINT must hold
        # the family lock — two replica threads asking for the same new
        # label concurrently would otherwise each construct a child and one
        # thread's observations would land in an orphan the exposition
        # never sees (the check-then-act race CONC603 flags)
        c = self.children.get(label_values)
        if c is None:
            if len(label_values) != len(self.label_names):
                raise ValueError(
                    f"{self.name}: expected labels {self.label_names}, "
                    f"got {label_values}"
                )
            with self._lock:
                c = self.children.get(label_values)
                if c is None:
                    c = (
                        Histogram(self.buckets)
                        if self.kind == "histogram"
                        else _KINDS[self.kind]()
                    )
                    self.children[label_values] = c
        return c


class MetricsRegistry:
    """Process-local metric registry. Registration is idempotent: asking for
    an existing name returns the SAME family (kind/labels must match — a
    mismatch is a programming error, raised loudly)."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    # ---- registration ----------------------------------------------------

    def _register(self, name, kind, help_text, labels, buckets=None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != tuple(labels) or (
                    kind == "histogram" and fam.buckets != tuple(buckets)
                ):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"kind/labels/buckets"
                    )
                return fam
            fam = _Family(name, kind, help_text, labels, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "", labels: Sequence[str] = ()):
        fam = self._register(name, "counter", help_text, labels)
        return fam if labels else fam.child(())

    def gauge(self, name: str, help_text: str = "", labels: Sequence[str] = ()):
        fam = self._register(name, "gauge", help_text, labels)
        return fam if labels else fam.child(())

    def histogram(
        self,
        name: str,
        help_text: str = "",
        buckets: Sequence[float] = LATENCY_MS_BUCKETS,
        labels: Sequence[str] = (),
    ):
        fam = self._register(name, "histogram", help_text, labels, buckets)
        return fam if labels else fam.child(())

    # ---- exposition ------------------------------------------------------

    def snapshot(self) -> Dict:
        """JSON-able view of every family (the ``--metrics-out`` format)."""
        out: Dict[str, Dict] = {}
        with self._lock:
            for name, fam in sorted(self._families.items()):
                samples = []
                # copy under the FAMILY lock: minting happens there, not
                # under the registry lock — iterating the live dict while a
                # replica thread mints a new label child would raise
                # mid-scrape
                with fam._lock:
                    children = sorted(fam.children.items())
                for lv, child in children:
                    labels = dict(zip(fam.label_names, lv))
                    if fam.kind == "histogram":
                        samples.append(
                            {
                                "labels": labels,
                                "sum": child.sum,
                                "count": child.count,
                                "buckets": {
                                    ("+Inf" if i == len(child.bounds) else
                                     _fmt_value(child.bounds[i])): c
                                    for i, c in enumerate(child.cumulative())
                                },
                            }
                        )
                    else:
                        samples.append({"labels": labels, "value": child.value})
                out[name] = {
                    "type": fam.kind,
                    "help": fam.help,
                    "samples": samples,
                }
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            for name, fam in sorted(self._families.items()):
                if fam.help:
                    lines.append(f"# HELP {name} {fam.help}")
                lines.append(f"# TYPE {name} {fam.kind}")
                with fam._lock:  # same copy-before-iterate as snapshot()
                    children = sorted(fam.children.items())
                for lv, child in children:
                    if fam.kind == "histogram":
                        cum = child.cumulative()
                        for i, c in enumerate(cum):
                            le = (
                                "+Inf" if i == len(child.bounds)
                                else _fmt_value(child.bounds[i])
                            )
                            extra = 'le="%s"' % le
                            lines.append(
                                f"{name}_bucket"
                                f"{_fmt_labels(fam.label_names, lv, extra)} {c}"
                            )
                        lines.append(
                            f"{name}_sum{_fmt_labels(fam.label_names, lv)} "
                            f"{_fmt_value(child.sum)}"
                        )
                        lines.append(
                            f"{name}_count{_fmt_labels(fam.label_names, lv)} "
                            f"{child.count}"
                        )
                    else:
                        lines.append(
                            f"{name}{_fmt_labels(fam.label_names, lv)} "
                            f"{_fmt_value(child.value)}"
                        )
        return "\n".join(lines) + "\n"

    def family_names(self) -> List[str]:
        """Sorted names of every registered family (the code half of the
        docs/OBSERVABILITY.md catalog-drift check)."""
        with self._lock:
            return sorted(self._families)

    def reset(self) -> None:
        with self._lock:
            self._families.clear()


_FAMILY_NAME_RE = re.compile(r"\bnxdi_[a-z0-9_]+")

#: exposition-format suffixes: a doc mentioning ``nxdi_x_bucket`` /
#: ``_sum`` / ``_count`` refers to the ``nxdi_x`` histogram family
_EXPOSITION_SUFFIXES = ("_bucket", "_sum", "_count")


def catalog_drift(
    doc_text: str, family_names: Sequence[str]
) -> Tuple[List[str], List[str]]:
    """Two-way drift between the documented metric catalog and the
    registered families: returns ``(undocumented, unregistered)`` —
    families in ``family_names`` that ``doc_text`` never mentions, and
    ``nxdi_*`` names the doc mentions that no family registers. Both lists
    empty == the catalog is exact (pinned by tests/test_telemetry.py)."""
    registered = set(family_names)
    documented = set()
    for name in _FAMILY_NAME_RE.findall(doc_text):
        for suffix in _EXPOSITION_SUFFIXES:
            if name.endswith(suffix) and name[: -len(suffix)] in registered:
                name = name[: -len(suffix)]
                break
        documented.add(name)
    undocumented = sorted(registered - documented)
    unregistered = sorted(documented - registered)
    return undocumented, unregistered


# process-default registry: the demo/bench ``--metrics-out`` target and the
# registry :func:`..tracing.default_session` records into
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
