"""Runtime telemetry: serving metrics, request-lifecycle tracing, spans.

Host-side, zero-device-round-trip observability (docs/OBSERVABILITY.md):
recording piggybacks on fetches the runtime already performs; tpulint rule
TPU107 statically forbids any recording call under a jit trace.
"""

from neuronx_distributed_inference_tpu.telemetry.metrics import (
    ACCEPT_LEN_BUCKETS,
    CHUNK_COUNT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    LATENCY_MS_BUCKETS,
    PROMETHEUS_CONTENT_TYPE,
    MetricsRegistry,
    default_registry,
)
from neuronx_distributed_inference_tpu.telemetry.ops_server import OpsServer
from neuronx_distributed_inference_tpu.telemetry.slo_monitor import (
    SloMonitor,
    judge,
)
from neuronx_distributed_inference_tpu.telemetry.spans import (
    SpanStore,
    to_chrome_trace,
)
from neuronx_distributed_inference_tpu.telemetry.tracing import (
    RequestTrace,
    TelemetrySession,
    default_session,
    enable_default_session,
    load_events,
    set_default_session,
)

__all__ = [
    "ACCEPT_LEN_BUCKETS",
    "CHUNK_COUNT_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "LATENCY_MS_BUCKETS",
    "PROMETHEUS_CONTENT_TYPE",
    "MetricsRegistry",
    "OpsServer",
    "RequestTrace",
    "SloMonitor",
    "SpanStore",
    "TelemetrySession",
    "default_registry",
    "default_session",
    "enable_default_session",
    "judge",
    "load_events",
    "set_default_session",
    "to_chrome_trace",
]
