"""Causal span trees over the flat telemetry event stream (ISSUE 19).

A :class:`SpanStore` holds the request- and replica-level timeline of one
run as *spans* (named intervals with a parent pointer), *instants* (point
events — chaos kills, quarantines, health transitions) and *flow events*
(the "this failover incarnation continues that one" arrows). It is pure
host-side bookkeeping on the session clock — the recording sites live in
:mod:`.tracing` and never add a device fetch.

Determinism contract: span ids are CONTENT-derived (request ids,
incarnation indices, replica step counters), never allocation-order
handles, and all timestamps come from the caller's (virtual) clock — so a
seeded workload drain records the IDENTICAL span tree under sequential and
``router_threading`` stepping (pinned by tests/test_obs_timeline.py). Only
the internal append order may differ across modes; :func:`to_chrome_trace`
sorts, so the exported JSON is byte-comparable too.

Thread safety (CONC601): one SpanStore is shared by every replica worker
of a threaded router — every mutation happens under ``self._lock``
(lock level between the telemetry session's RLock and the metric
families'). The store is bounded: past ``max_spans`` the oldest COMPLETED
spans evict (open spans never do — they are the live tree) and the drop is
counted, so a long chaos drain cannot grow span memory without limit.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Span",
    "Instant",
    "SpanStore",
    "to_chrome_trace",
]


@dataclass
class Span:
    """One named interval on a track. ``t_end is None`` == still open."""

    span_id: str
    name: str
    track: str
    t_start: float
    t_end: Optional[float] = None
    parent_id: Optional[str] = None
    #: sub-track within the track (one tid per lane in the Chrome export);
    #: request spans use their base request id so each request gets a row
    lane: str = "0"
    attrs: Dict[str, object] = field(default_factory=dict)


@dataclass
class Instant:
    """A point event (Chrome ``ph:"i"``): kills, quarantines, transitions."""

    name: str
    track: str
    ts: float
    lane: str = "0"
    attrs: Dict[str, object] = field(default_factory=dict)


@dataclass
class FlowPoint:
    """One endpoint of a flow arrow (Chrome ``ph:"s"``/``"f"`` pair).
    ``phase`` is ``"s"`` (source) or ``"f"`` (destination); arrows render
    only when both phases of a ``flow_id`` exist."""

    flow_id: str
    phase: str
    track: str
    ts: float
    lane: str = "0"


class SpanStore:
    """Bounded, lock-protected store for one session's span timeline."""

    def __init__(self, max_spans: int = 10000):
        self._lock = threading.RLock()
        self._open: Dict[str, Span] = {}
        self._done: deque = deque()
        self._instants: deque = deque()
        self._flows: List[FlowPoint] = []
        self.max_spans = int(max_spans)
        self.dropped = 0  # completed spans / instants evicted past the cap

    # ---- recording (all mutation under the store lock) -------------------

    def begin(
        self,
        span_id: str,
        name: str,
        track: str,
        t: float,
        parent_id: Optional[str] = None,
        lane: str = "0",
        **attrs,
    ) -> None:
        """Open a span. Idempotent on ``span_id`` — a duplicate begin (a
        re-admission re-entering a phase) keeps the FIRST interval."""
        with self._lock:
            if span_id in self._open:
                return
            self._open[span_id] = Span(
                span_id=span_id, name=name, track=track, t_start=float(t),
                parent_id=parent_id, lane=lane, attrs=dict(attrs),
            )

    def end(self, span_id: str, t: float, **attrs) -> None:
        """Close an open span (unknown/already-closed ids are ignored — a
        terminal record may race a failover close; first close wins)."""
        with self._lock:
            sp = self._open.pop(span_id, None)
            if sp is None:
                return
            sp.t_end = max(float(t), sp.t_start)
            if attrs:
                sp.attrs.update(attrs)
            if len(self._done) >= self.max_spans:
                self._done.popleft()
                self.dropped += 1
            self._done.append(sp)

    def is_open(self, span_id: str) -> bool:
        with self._lock:
            return span_id in self._open

    def set_attrs(self, span_id: str, **attrs) -> None:
        with self._lock:
            sp = self._open.get(span_id)
            if sp is not None:
                sp.attrs.update(attrs)

    def instant(self, name: str, track: str, ts: float, lane: str = "0",
                **attrs) -> None:
        with self._lock:
            if len(self._instants) >= self.max_spans:
                self._instants.popleft()
                self.dropped += 1
            self._instants.append(Instant(
                name=name, track=track, ts=float(ts), lane=lane,
                attrs=dict(attrs),
            ))

    def flow(self, flow_id: str, phase: str, track: str, ts: float,
             lane: str = "0") -> None:
        with self._lock:
            self._flows.append(FlowPoint(
                flow_id=flow_id, phase=phase, track=track, ts=float(ts),
                lane=lane,
            ))

    # ---- reading ---------------------------------------------------------

    def snapshot(self) -> Tuple[List[Span], List[Instant], List[FlowPoint]]:
        """Copy the whole store under the lock — completed spans first,
        then the still-open ones (shallow-copied so a racing ``end()``
        cannot mutate what the caller serializes; the ISSUE-19 bugfix)."""
        with self._lock:
            spans = [Span(**vars(s)) for s in self._done]
            spans += [Span(**vars(s)) for s in self._open.values()]
            instants = [Instant(**vars(i)) for i in self._instants]
            flows = list(self._flows)
        return spans, instants, flows

    def span_tree(self) -> Dict[str, tuple]:
        """The determinism pin's comparable form:
        ``{span_id: (name, parent_id, track, lane, t_start, t_end)}`` —
        order-free, so sequential and threaded drains compare equal."""
        spans, _, _ = self.snapshot()
        return {
            s.span_id: (s.name, s.parent_id, s.track, s.lane,
                        s.t_start, s.t_end)
            for s in spans
        }


def to_chrome_trace(
    spans: List[Span],
    instants: List[Instant],
    flows: List[FlowPoint],
    *,
    now: float,
    dropped: int = 0,
) -> dict:
    """Build a Chrome trace-event JSON object (Perfetto-loadable) from a
    span-store snapshot. One ``pid`` (process track) per span track —
    ``tenant:*`` tracks beside ``replica:*`` / ``prefill:*`` / ``driver``
    — and one ``tid`` per lane within a track (each request gets its own
    row inside its tenant track). Timestamps are normalized to the
    earliest observation and scaled seconds→µs; open spans close at
    ``now``. Flow arrows emit only when both endpoints of a flow id exist
    (the schema check pins every emitted flow id pairs)."""
    tracks = sorted(
        {s.track for s in spans}
        | {i.track for i in instants}
        | {f.track for f in flows}
    )
    pid_of = {tr: i + 1 for i, tr in enumerate(tracks)}
    lanes: Dict[str, set] = {tr: set() for tr in tracks}
    for s in spans:
        lanes[s.track].add(s.lane)
    for i in instants:
        lanes[i.track].add(i.lane)
    for f in flows:
        lanes[f.track].add(f.lane)
    tid_of = {
        (tr, lane): j + 1
        for tr in tracks
        for j, lane in enumerate(sorted(lanes[tr]))
    }
    all_ts = (
        [s.t_start for s in spans]
        + [i.ts for i in instants]
        + [f.ts for f in flows]
    )
    t0 = min(all_ts) if all_ts else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    events: List[dict] = []
    for tr in tracks:
        events.append({
            "ph": "M", "name": "process_name", "pid": pid_of[tr], "tid": 0,
            "ts": 0, "args": {"name": tr},
        })
    for s in spans:
        end = s.t_end if s.t_end is not None else max(now, s.t_start)
        ev = {
            "ph": "X", "name": s.name, "cat": "nxdi",
            "pid": pid_of[s.track], "tid": tid_of[(s.track, s.lane)],
            "ts": us(s.t_start), "dur": round((end - s.t_start) * 1e6, 3),
            "args": {"span_id": s.span_id, **s.attrs},
        }
        if s.parent_id:
            ev["args"]["parent"] = s.parent_id
        if s.t_end is None:
            ev["args"]["open"] = True
        events.append(ev)
    for i in instants:
        events.append({
            "ph": "i", "name": i.name, "cat": "nxdi", "s": "t",
            "pid": pid_of[i.track], "tid": tid_of[(i.track, i.lane)],
            "ts": us(i.ts), "args": dict(i.attrs),
        })
    by_flow: Dict[str, Dict[str, FlowPoint]] = {}
    for f in flows:
        by_flow.setdefault(f.flow_id, {})[f.phase] = f
    for fid in sorted(by_flow):
        pair = by_flow[fid]
        if "s" not in pair or "f" not in pair:
            continue  # an unpaired endpoint (run cut mid-failover) is mute
        for phase in ("s", "f"):
            f = pair[phase]
            events.append({
                "ph": phase, "name": "failover", "cat": "nxdi", "id": fid,
                "pid": pid_of[f.track], "tid": tid_of[(f.track, f.lane)],
                "ts": us(f.ts),
            })
            if phase == "f":
                events[-1]["bp"] = "e"
    # a deterministic serialization independent of record interleaving
    events.sort(key=lambda e: (
        e["ts"], e["ph"], e["pid"], e["tid"], e["name"],
        str(e.get("id", "")), str(e.get("args", "")),
    ))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"dropped_spans": dropped},
    }


def dump_chrome_trace(trace: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(trace, f)
