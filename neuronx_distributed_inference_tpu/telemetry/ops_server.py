"""Dependency-free live ops surface: /metrics, /health, /slo (ISSUE 19).

A :class:`OpsServer` binds a stdlib ``ThreadingHTTPServer`` on an ephemeral
(or pinned) port and serves three read-only routes while a drain runs:

- ``/metrics`` — the session registry's Prometheus text exposition
  (``MetricsRegistry.prometheus_text`` already snapshots per family under
  its lock — the PR-13 copy-then-render pattern — so a scrape racing the
  router thread reads a consistent family).
- ``/health`` — per-replica health/occupancy/backlog JSON from the
  caller-provided ``health_fn`` (the router's gauge view).
- ``/slo`` — the live :class:`~.slo_monitor.SloMonitor` snapshot: windowed
  attainment + burn rate per tenant, the operable control signal the
  ROADMAP "elastic fleet" item closes on.

Threading model (CONC601–603): the ONLY mutable state is held by the
server object and written once at init (init-confined); the handler reads
it and calls the three callables, each of which takes its OWN lock
(registry / monitor / router) — the HTTP threads never hold a runtime lock
across a blocking socket write because the payload is fully rendered
before ``wfile.write``. ``stop()`` joins the serve thread without holding
any session lock.

Host-side only: nothing here touches jax or a device — TPU107-clean by
construction, and import stays stdlib-only so the module loads anywhere.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from neuronx_distributed_inference_tpu.telemetry import metrics as metrics_mod

__all__ = ["OpsServer"]


class _OpsHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer + the three route backends (init-confined)."""

    daemon_threads = True

    def __init__(self, addr, registry, health_fn, slo_fn):
        super().__init__(addr, _OpsHandler)
        self.registry = registry
        self.health_fn = health_fn
        self.slo_fn = slo_fn


class _OpsHandler(BaseHTTPRequestHandler):
    """Read-only GET router. No attribute writes, no runtime locks held —
    each route renders its full payload (the callables lock internally)
    and then writes it out."""

    server: _OpsHTTPServer

    def do_GET(self):  # noqa: N802 (http.server naming contract)
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            body = self.server.registry.prometheus_text().encode()
            ctype = metrics_mod.PROMETHEUS_CONTENT_TYPE
        elif path == "/health":
            fn = self.server.health_fn
            payload = fn() if fn is not None else {}
            body = json.dumps(payload, sort_keys=True).encode()
            ctype = "application/json"
        elif path == "/slo":
            fn = self.server.slo_fn
            payload = fn() if fn is not None else {}
            body = json.dumps(payload, sort_keys=True).encode()
            ctype = "application/json"
        else:
            body = b"not found: routes are /metrics /health /slo\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:
        # scrapes every few seconds must not spam stderr
        pass


class OpsServer:
    """Threaded HTTP endpoint over one telemetry registry.

    ``health_fn`` / ``slo_fn`` are zero-arg callables returning
    JSON-serializable dicts (``ServingRouter.diagnostic_snapshot`` and
    ``SloMonitor.snapshot`` are the intended bindings); either may be None
    and the route serves ``{}``. ``port=0`` binds an ephemeral port —
    read ``self.port`` after :meth:`start`.
    """

    def __init__(
        self,
        registry,
        health_fn: Optional[Callable[[], dict]] = None,
        slo_fn: Optional[Callable[[], dict]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.registry = registry
        self.health_fn = health_fn
        self.slo_fn = slo_fn
        self.host = host
        self.port = int(port)
        self._httpd: Optional[_OpsHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            return self.port
        self._httpd = _OpsHTTPServer(
            (self.host, self.port), self.registry, self.health_fn,
            self.slo_fn,
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="nxdi-ops-server",
            daemon=True,
        )
        self._thread.start()
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        """Shut the server down and JOIN the serve thread (clean teardown
        is part of the tier-1 smoke — no daemon-thread leak past stop)."""
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=10.0)

    def __enter__(self) -> "OpsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
