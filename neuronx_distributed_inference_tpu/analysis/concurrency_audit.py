"""Concurrency-contract analyzer (CONC6xx): the host threading model of the
serving stack as a statically audited, baseline-pinned contract.

The thread-per-replica router (``TpuConfig.router_threading``,
runtime/router.py) is only safe under a specific confinement model: ONLY
``ReplicaHandle.step()`` runs on worker threads; placement, admission,
failover harvesting, terminal sync and every gauge stay on the router
thread, which blocks on the per-step barrier while workers run — so
per-replica objects are touched by at most one thread at a time, and the
only state crossing replicas (the shared telemetry session and its metric
instruments) must be lock-protected. A dynamic test suite cannot reliably
catch a violation of that model (a data race is a probability, not a
behavior), so — in the tradition of the graph (PR 1), shard/memory (PR 5)
and cost (PR 11) contracts — this suite proves the model over the AST +
traced call graph and pins the resulting census to
``analysis/conc_baseline.json``:

- **CONC601 shared-mutable-state census** — every attribute/container WRITE
  site in runtime/router.py, runtime/replica.py, runtime/serving.py,
  runtime/faults.py and telemetry/ is classified:

  - ``replica-step-confined`` — a write to replica-owned state (session,
    handle, request, injector, app/cache, worker cell) reachable from the
    worker entry points: safe because each replica owns its objects and is
    stepped by one thread.
  - ``router-thread`` — a write NOT reachable from any worker entry: it can
    only execute on the router/driver thread (placement, admission,
    harvesting — phases the barrier serializes against the workers).
  - ``lock-protected`` — syntactically inside a ``with <lock>:`` region.
  - ``init-confined`` — ``self.*`` writes inside the owner's
    ``__init__``/``__post_init__`` (the object is unpublished).

  Anything else — shared (telemetry/registry) state written from a worker
  path without a lock, router-owned state written from a worker path, a
  write whose owner the analyzer cannot resolve, a module global mutated
  from a worker path — is an ERROR finding with zero baseline budget. The
  classified census is pinned: new shared state (a new attribute, or an
  existing write drifting to a different classification) trips the gate.
- **CONC602 lock discipline** — locks are acquired only via ``with`` (bare
  ``.acquire()``/``.release()`` is an error); nested acquisition must follow
  the single global order **router (0) → replica/session (1) → telemetry
  session (2) → metric instrument (3)** — for every ``with <lock>`` region
  the traced call graph is walked and a reachable acquisition of a
  lower-or-equal level is a cycle risk (same-identity re-entry is allowed
  only for locks constructed as ``threading.RLock``); and no BLOCKING call
  (``jax.device_get`` / ``block_until_ready``, an in-flight ``.result()`` /
  ``np.asarray`` fetch, ``time.sleep``, ``.join()``/``.wait()``, file or
  socket I/O) may execute while holding a router-level (level-0) lock — a
  block under the router lock would stall every replica.
- **CONC603 telemetry atomicity** — every Counter/Gauge/Histogram mutation
  must go through the registry's atomic ``inc``/``set``/``observe``: a
  read-modify-write on instrument internals (``.value``/``.sum``/
  ``.count``/``._value``/bucket lists) anywhere outside the locked
  instrument methods in telemetry/metrics.py is an error. (``+=`` on a
  Python float is multiple bytecodes; the GIL does not make it atomic.)
- **CONC604 JAX-object thread-ownership census** — replica device state
  (``kv_cache``, params, the in-flight ``_pending``/``_draft_prop`` device
  arrays, the runners) is touched only by the replica's confinement set
  (session + handle). ``ServingRouter`` code reaching through
  ``h.session.<attr>`` may only read committed host-side snapshots: the
  touched-attribute census is baseline-pinned (a NEW router→session touch
  is reviewed like a collective), and touching a device-state attribute is
  an error outright.

Like the other suites: ``python -m neuronx_distributed_inference_tpu.analysis
--suites conc`` exits 0 on a clean tree, ``--write-baseline`` regenerates
``conc_baseline.json`` and prints the unified diff, and the ``--json``
report carries a ``"concurrency"`` section with the classification
breakdown. Suppression: ``# conc: ignore[CONC601]`` on the offending line
or its ``def`` line. See docs/STATIC_ANALYSIS.md "Concurrency audit".
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from neuronx_distributed_inference_tpu.analysis.findings import (
    Baseline,
    CONTAINER_MUTATORS,
    Finding,
    SEV_ERROR,
    SEV_WARNING,
)

PACKAGE = "neuronx_distributed_inference_tpu"
BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "conc_baseline.json"

#: the audited surface — the serving host layers the threaded router makes
#: concurrent, matched by relpath suffix so fixture trees audit identically
SCOPE_SUFFIXES = (
    "runtime/router.py",
    "runtime/replica.py",
    "runtime/serving.py",
    "runtime/faults.py",
    "telemetry/__init__.py",
    "telemetry/metrics.py",
    "telemetry/tracing.py",
    # the open-loop workload driver (ISSUE 14): it steps the router — and
    # under router_threading its spec accept-gate closure is CALLED from
    # replica workers — so its write sites join the census like the
    # router's own
    "workload/driver.py",
    # the disaggregated KV hand-off (ISSUE 15): extract/inject/validate run
    # on the router thread during the placement phase, writing the prefill
    # and decode apps' caches — their write sites join the census so a
    # future worker-reachable hand-off cannot slip in unclassified
    "runtime/disaggregated.py",
    # the observability layer (ISSUE 19): the span store and SLO monitor
    # are written from replica workers (via TelemetrySession record hooks)
    # AND read by the ops-server scrape thread, so both join the census as
    # SHARED; the ops server itself adds a third thread kind to the model
    # (its handler threads, entered at do_GET)
    "telemetry/spans.py",
    "telemetry/slo_monitor.py",
    "telemetry/ops_server.py",
)

# ---------------------------------------------------------------------------
# ownership model: which class owns a write decides what discipline it needs
# ---------------------------------------------------------------------------

#: per-replica objects: each replica owns exactly one of each, and the
#: barrier guarantees at most one thread (its worker, or the router between
#: barriers) touches them at a time. ``TpuApplication`` is the pseudo-class
#: for ``session.app``/``session.draft`` (the per-replica model application
#: holding params + the donated KV cache).
#: PrefillReplicaHandle/DisaggregatedPipeline (ISSUE 15) carry the replica
#: discipline: a tier member's app/health is touched by exactly one thread
#: at a time — the router thread, synchronously, during the placement
#: phase's hand-off (workers never run hand-offs; CONC604 keeps it so)
REPLICA_OWNED = frozenset({
    "ServingSession", "SpeculativeServingSession", "ReplicaHandle",
    "Request", "FaultInjector", "RequestTrace", "TpuApplication",
    "_ReplicaStepWorker", "WatchdogError",
    "PrefillReplicaHandle", "DisaggregatedPipeline",
    "_HealthStateMachine",  # the shared health-machine base of both handles
})

#: router-global objects: written ONLY by the router thread — a write
#: reachable from a worker entry is an error, not a census entry.
#: WorkloadDriver/VirtualClock/WorkloadResult (workload/driver.py) run the
#: open-loop admission/scoring loop on the SAME thread the router's
#: placement phases run on (the driver calls router.step() between its own
#: phases), so they carry the router-thread discipline.
ROUTER_OWNED = frozenset({
    "ServingRouter", "RouterRequest",
    "WorkloadDriver", "VirtualClock", "WorkloadResult",
    # the ops server's lifecycle state (thread handle, bound port) is
    # written only by whoever starts/stops it — the router/driver thread;
    # its handler threads read the registry/snapshot callbacks but never
    # write OpsServer attributes (CONC601 keeps it so)
    "OpsServer",
})

#: state shared ACROSS replicas: every worker thread records into one
#: telemetry session / registry, so worker-reachable writes must be
#: lock-protected
SHARED = frozenset({
    "TelemetrySession", "MetricsRegistry", "_Family",
    "Counter", "Gauge", "Histogram",
    # ISSUE 19: span timelines + SLO windows are recorded from worker
    # threads through the session's record hooks and scraped by the ops
    # server's handler threads — every mutation must hold their own lock
    "SpanStore", "SloMonitor",
})

#: the worker thread entry points — the ONLY code the thread-per-replica
#: pool runs. Everything transitively reachable from these is the
#: "replica step thread" set W.
WORKER_ENTRIES = (
    ("ReplicaHandle", "step"),
    ("_ReplicaStepWorker", "run"),
    # the ops server's per-connection handler threads (ThreadingHTTPServer)
    # — everything a scrape can reach must carry worker discipline
    ("_OpsHandler", "do_GET"),
)

# ---------------------------------------------------------------------------
# type environment: how receiver expressions resolve to owner classes.
# Deliberately repo-specific configuration (like tpulint's hot-path sets) —
# the analyzer is a contract for THIS codebase, not a general type checker.
# ---------------------------------------------------------------------------

#: (owner class or "*", attribute) -> class of that attribute
ATTR_TYPES = {
    ("*", "session"): "ServingSession",
    ("*", "tel"): "TelemetrySession",
    ("*", "faults"): "FaultInjector",
    ("*", "registry"): "MetricsRegistry",
    ("*", "app"): "TpuApplication",
    ("*", "draft"): "TpuApplication",
    ("_ReplicaStepWorker", "handle"): "ReplicaHandle",
    ("WorkloadDriver", "result"): "WorkloadResult",
    ("WorkloadDriver", "clock"): "VirtualClock",
    ("*", "prefill_app"): "TpuApplication",
    ("*", "decode_app"): "TpuApplication",
    ("*", "spans"): "SpanStore",
    ("*", "slo_monitor"): "SloMonitor",
}

#: (owner class or "*", container attribute) -> element/value class
ELEM_TYPES = {
    ("ServingRouter", "replicas"): "ReplicaHandle",
    ("ServingRouter", "alive_replicas"): "ReplicaHandle",
    ("ServingRouter", "prefill_replicas"): "PrefillReplicaHandle",
    ("ServingRouter", "alive_prefill_replicas"): "PrefillReplicaHandle",
    ("ServingRouter", "requests"): "RouterRequest",
    ("ServingRouter", "rejected"): "RouterRequest",
    ("ServingRouter", "pending"): "RouterRequest",
    ("ServingRouter", "_workers"): "_ReplicaStepWorker",
    ("ServingSession", "slots"): "Request",
    ("ServingSession", "active"): "Request",
    ("ServingSession", "decoding"): "Request",
    ("ServingSession", "prefilling"): "Request",
    ("ServingSession", "_readmit"): "Request",
    ("ServingSession", "requests"): "Request",
    ("ServingSession", "rejected"): "Request",
    ("ReplicaHandle", "owned"): "RouterRequest",
    ("TelemetrySession", "traces"): "RequestTrace",
    ("TelemetrySession", "completed"): "RequestTrace",
    ("MetricsRegistry", "_families"): "_Family",
}

#: last-resort receiver-name hints (an explicit annotation or an inferred
#: assignment always wins); the census keeps the analyzer honest — a
#: mis-hinted owner shows up as census drift
VAR_NAME_HINTS = {
    "req": "Request", "r": "Request", "sreq": "Request", "victim": "Request",
    "rreq": "RouterRequest",
    "h": "ReplicaHandle", "handle": "ReplicaHandle",
    "tr": "RequestTrace",
    "sess": "ServingSession", "session": "ServingSession",
    "fam": "_Family", "tel": "TelemetrySession",
    "router": "ServingRouter",
    "w": "_ReplicaStepWorker",
    "app": "TpuApplication", "draft_app": "TpuApplication",
    "drv": "WorkloadDriver", "vc": "VirtualClock",
    "mon": "SloMonitor", "store": "SpanStore",
    "ph": "PrefillReplicaHandle",
    "pre": "TpuApplication", "dec": "TpuApplication",
    "pipe": "DisaggregatedPipeline",
}

#: container-mutating method names (a call through these IS a write) —
#: shared with tpulint's TPU109 so lint and audit agree on what a write is
MUTATORS = CONTAINER_MUTATORS

#: lock acquisition hierarchy: nested ``with <lock>`` must strictly
#: INCREASE in level (router outermost, metric instruments innermost; the
#: registry may hold its lock while copying a family's child table, and a
#: family holds its lock while minting a child instrument)
LOCK_LEVELS = {
    "ServingRouter": 0, "RouterRequest": 0,
    "WorkloadDriver": 0, "VirtualClock": 0, "WorkloadResult": 0,
    "ReplicaHandle": 1, "ServingSession": 1, "SpeculativeServingSession": 1,
    "Request": 1, "FaultInjector": 1, "_ReplicaStepWorker": 1,
    "PrefillReplicaHandle": 1, "DisaggregatedPipeline": 1,
    "_HealthStateMachine": 1,
    "TelemetrySession": 2,
    # the span store and SLO monitor sit BELOW the session: record hooks
    # take the session lock then the store/monitor lock, never the reverse
    # (export snapshots under the session lock copy, serialize outside)
    "SpanStore": 3, "SloMonitor": 3, "OpsServer": 2,
    "MetricsRegistry": 3,
    "_Family": 4,
    "Counter": 5, "Gauge": 5, "Histogram": 5,
}
#: fallback lock level by scope file when the lock's owner class is unknown
MODULE_LOCK_LEVELS = {
    "workload/driver.py": 0,
    "runtime/router.py": 0,
    "runtime/replica.py": 1,
    "runtime/disaggregated.py": 1,
    "runtime/serving.py": 1,
    "runtime/faults.py": 1,
    "telemetry/tracing.py": 2,
    "telemetry/__init__.py": 2,
    "telemetry/spans.py": 3,
    "telemetry/slo_monitor.py": 3,
    "telemetry/ops_server.py": 2,
    "telemetry/metrics.py": 3,
}

#: calls that can block (device sync, sleeps, thread joins, file/socket IO)
#: — forbidden while holding a router-level lock (CONC602)
BLOCKING_ATTRS = frozenset({
    "device_get", "block_until_ready", "item", "result", "join", "wait",
    "sleep", "asarray", "array", "acquire", "read", "write", "recv", "send",
    "connect",
})
BLOCKING_NAMES = frozenset({"open", "device_get", "block_until_ready",
                            "sleep", "input"})

#: CONC603: instrument-internal attributes no call site may read-modify-write
INSTRUMENT_INTERNALS = frozenset({"value", "sum", "count", "_value"})
INSTRUMENT_BUCKETS = frozenset({"counts", "buckets"})
INSTRUMENT_CLASSES = frozenset({"Counter", "Gauge", "Histogram", "_Family"})

#: CONC604: replica device state the router must never reach through
#: ``h.session.<attr>`` (stepping included: it belongs to the handle/worker)
DEVICE_STATE_ATTRS = frozenset({
    "kv_cache", "params", "_pending", "_draft_prop", "mixed_runner",
    "draft", "app_params", "token_generation_model",
    "context_encoding_model", "step", "_step_inner",
})

_PRAGMA_RE = re.compile(r"#\s*conc:\s*ignore(?:\[([A-Z0-9, ]+)\])?")

#: set by :func:`run` — the classification breakdown the CLI embeds in --json
_LAST_REPORT: Dict = {}


# ---------------------------------------------------------------------------
# module / function indexing
# ---------------------------------------------------------------------------


@dataclass
class _Func:
    module: str  # scope-relative path (matched suffix)
    cls: str  # "" for module-level functions
    name: str
    node: ast.AST
    bases: Tuple[str, ...] = ()
    calls: Set[Tuple[str, str]] = field(default_factory=set)  # (cls, name)
    worker: bool = False  # reachable from a WORKER_ENTRY

    @property
    def key(self) -> Tuple[str, str]:
        return (self.cls, self.name)

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class _LockRegion:
    func: "_Func"
    identity: Tuple[str, str]  # (owner class or <module...>, attr/name)
    level: int
    lineno: int
    end_lineno: int
    node: ast.With


class _Module:
    def __init__(self, path: pathlib.Path, scope_rel: str):
        self.path = path
        self.rel = scope_rel
        self.source = path.read_text()
        self.tree = ast.parse(self.source, filename=str(path))
        self.pragmas = self._collect_pragmas()
        # module-level names assigned at import time (the TPU109 smell's
        # census side) — writes through them from functions are module-
        # global writes
        self.module_globals: Set[str] = set()
        for node in self.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    self.module_globals.add(t.id)

    def _collect_pragmas(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.source.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                rules = m.group(1)
                out[i] = {r.strip() for r in rules.split(",")} if rules else {"*"}
        return out

    def suppressed(self, line: int, rule: str, def_line: Optional[int] = None) -> bool:
        for ln in (line, def_line):
            if ln is None:
                continue
            rules = self.pragmas.get(ln)
            if rules and ("*" in rules or rule in rules):
                return True
        return False


def _ann_to_type(ann, classes: Set[str]) -> Tuple[Optional[str], Optional[str]]:
    """(scalar type, container element type) from an annotation node."""
    if isinstance(ann, ast.Name) and ann.id in classes:
        return ann.id, None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str) and ann.value in classes:
        return ann.value, None
    if isinstance(ann, ast.Subscript):
        # List[Request] / Sequence[ReplicaHandle] / Dict[str, Request]
        sl = ann.slice
        elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        for e in reversed(elts):  # Dict value type wins
            t, _ = _ann_to_type(e, classes)
            if t:
                return None, t
    return None, None


class _Analyzer:
    def __init__(self, files: List[Tuple[pathlib.Path, str]]):
        self.modules: List[_Module] = [_Module(p, rel) for p, rel in files]
        self.findings: List[Finding] = []
        # class -> (module, bases); method tables per class
        self.class_bases: Dict[str, Tuple[str, ...]] = {}
        self.methods: Dict[Tuple[str, str], List[_Func]] = {}
        self.funcs: List[_Func] = []
        self.lock_kinds: Dict[Tuple[str, str], str] = {}  # identity -> lock|rlock
        self._index()
        self._build_env_and_calls()
        self._mark_worker_set()

    # ---- indexing --------------------------------------------------------

    def _index(self):
        for mod in self.modules:
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    bases = tuple(
                        b.id for b in node.bases if isinstance(b, ast.Name)
                    )
                    self.class_bases[node.name] = bases
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            self._add_func(mod, node.name, sub, bases)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_func(mod, "", node, ())
        # lock kinds: self.<attr> = threading.Lock()/RLock() anywhere
        for f in self.funcs:
            for n in ast.walk(f.node):
                if not (isinstance(n, ast.Assign) and isinstance(n.value, ast.Call)):
                    continue
                v = n.value.func
                kind = None
                if isinstance(v, ast.Attribute) and v.attr in ("Lock", "RLock"):
                    kind = "rlock" if v.attr == "RLock" else "lock"
                elif isinstance(v, ast.Name) and v.id in ("Lock", "RLock"):
                    kind = "rlock" if v.id == "RLock" else "lock"
                if kind is None:
                    continue
                for t in n.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.lock_kinds[(f.cls, t.attr)] = kind

    def _add_func(self, mod: _Module, cls: str, node, bases):
        f = _Func(module=mod.rel, cls=cls, name=node.name, node=node, bases=bases)
        f._mod = mod  # type: ignore[attr-defined]
        self.funcs.append(f)
        self.methods.setdefault((cls, node.name), []).append(f)
        # nested defs (dispatch closures): indexed as their own functions in
        # the same class context, with an implicit call edge from the parent
        for sub in ast.walk(node):
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub is not node
            ):
                nf = _Func(module=mod.rel, cls=cls, name=sub.name, node=sub,
                           bases=bases)
                nf._mod = mod  # type: ignore[attr-defined]
                self.funcs.append(nf)
                self.methods.setdefault((cls, sub.name), []).append(nf)
                f.calls.add((cls, sub.name))

    def _hierarchy(self, cls: str) -> Set[str]:
        """cls + its in-scope bases + in-scope subclasses (method resolution
        fans out over the whole hierarchy: the conservative direction)."""
        out = {cls}
        # bases (transitive)
        frontier = [cls]
        while frontier:
            c = frontier.pop()
            for b in self.class_bases.get(c, ()):
                if b not in out:
                    out.add(b)
                    frontier.append(b)
        # subclasses
        changed = True
        while changed:
            changed = False
            for c, bases in self.class_bases.items():
                if c not in out and any(b in out for b in bases):
                    out.add(c)
                    changed = True
        return out

    # ---- type environment ------------------------------------------------

    def _elem_type(self, owner: Optional[str], attr: str) -> Optional[str]:
        if owner:
            for c in self._hierarchy(owner):
                t = ELEM_TYPES.get((c, attr))
                if t:
                    return t
        return ELEM_TYPES.get(("*", attr))

    def _attr_type(self, owner: Optional[str], attr: str) -> Optional[str]:
        if owner:
            for c in self._hierarchy(owner):
                t = ATTR_TYPES.get((c, attr))
                if t:
                    return t
        return ATTR_TYPES.get(("*", attr))

    def _expr_type(self, f: _Func, env: Dict[str, str], expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id == "self" and f.cls:
                return f.cls
            t = env.get(expr.id)
            if t:
                return t
            return VAR_NAME_HINTS.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_type(f, env, expr.value)
            return self._attr_type(base, expr.attr)
        if isinstance(expr, ast.Subscript):
            v = expr.value
            if isinstance(v, ast.Attribute):
                base = self._expr_type(f, env, v.value)
                return self._elem_type(base, v.attr)
            if isinstance(v, ast.Name):
                return env.get("<elem>" + v.id)
            return None
        if isinstance(expr, ast.Call):
            fn = expr.func
            if isinstance(fn, ast.Name) and fn.id == "default_session":
                return "TelemetrySession"
            if isinstance(fn, ast.Attribute):
                if fn.attr in ("get", "pop", "popleft"):
                    # dict.get / dict.pop / deque.popleft yield the element
                    return self._expr_type(
                        f, env, ast.Subscript(value=fn.value, slice=ast.Constant(value=0))
                    )
                # constructor-ish call through a class name
            if isinstance(fn, ast.Name) and fn.id in self.class_bases:
                return fn.id
        return None

    def _build_env(self, f: _Func) -> Dict[str, str]:
        """name -> class for locals (annotations, inferred assignments,
        iteration over typed containers); '<elem>name' entries carry the
        element type of locally-bound container aliases."""
        env: Dict[str, str] = {}
        classes = set(self.class_bases) | {"TpuApplication", "RequestTrace"}
        args = f.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                t, elem = _ann_to_type(a.annotation, classes)
                if t:
                    env[a.arg] = t
                elif elem:
                    env["<elem>" + a.arg] = elem
        # two passes so chains like alive = self.alive_replicas; for h in
        # alive resolve regardless of textual order
        for _ in range(2):
            for n in ast.walk(f.node):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(
                    n.targets[0], ast.Name
                ):
                    name = n.targets[0].id
                    t = self._expr_type(f, env, n.value)
                    if t:
                        env[name] = t
                    elif isinstance(n.value, ast.Attribute):
                        base = self._expr_type(f, env, n.value.value)
                        elem = self._elem_type(base, n.value.attr)
                        if elem:
                            env["<elem>" + name] = elem
                elif isinstance(n, (ast.For, ast.comprehension)):
                    tgt = n.target
                    it = n.iter
                    # unwrap enumerate(...) / list()/sorted()/reversed() /
                    # .items()/.values() wrappers, any nesting order;
                    # enumerate and .items() shift the element to the
                    # SECOND tuple target
                    second_of_tuple = False
                    for _unwrap in range(3):
                        if not isinstance(it, ast.Call):
                            break
                        fn = it.func
                        if isinstance(fn, ast.Name) and fn.id in (
                            "enumerate", "list", "sorted", "reversed"
                        ) and it.args:
                            if fn.id == "enumerate":
                                second_of_tuple = True
                            it = it.args[0]
                        elif isinstance(fn, ast.Attribute) and fn.attr in (
                            "items", "values"
                        ):
                            if fn.attr == "items":
                                second_of_tuple = True
                            it = fn.value
                        else:
                            break
                    elem = None
                    if isinstance(it, ast.Attribute):
                        base = self._expr_type(f, env, it.value)
                        elem = self._elem_type(base, it.attr)
                    elif isinstance(it, ast.Name):
                        elem = env.get("<elem>" + it.id)
                    if elem is None:
                        continue
                    if isinstance(tgt, ast.Name) and not second_of_tuple:
                        env[tgt.id] = elem
                    elif isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2 and isinstance(
                        tgt.elts[1], ast.Name
                    ):
                        env[tgt.elts[1].id] = elem
        return env

    # ---- call graph + worker reachability --------------------------------

    def _build_env_and_calls(self):
        self._envs: Dict[int, Dict[str, str]] = {}
        # unique method names: a receiver of unknown type still resolves
        # when exactly one scope class defines the method
        by_name: Dict[str, List[Tuple[str, str]]] = {}
        for (cls, name), fns in self.methods.items():
            by_name.setdefault(name, []).append((cls, name))
        for f in self.funcs:
            env = self._build_env(f)
            self._envs[id(f)] = env
            for n in ast.walk(f.node):
                if not isinstance(n, ast.Call):
                    continue
                fn = n.func
                if isinstance(fn, ast.Name):
                    if (("", fn.id)) in self.methods:
                        f.calls.add(("", fn.id))
                    continue
                if not isinstance(fn, ast.Attribute):
                    continue
                m = fn.attr
                recv = fn.value
                if isinstance(recv, ast.Name) and recv.id == "self" and f.cls:
                    for c in self._hierarchy(f.cls):
                        if (c, m) in self.methods:
                            f.calls.add((c, m))
                    continue
                t = self._expr_type(f, env, recv)
                if t:
                    hit = False
                    for c in self._hierarchy(t):
                        if (c, m) in self.methods:
                            f.calls.add((c, m))
                            hit = True
                    if hit:
                        continue
                # unique-name fallback (never into a different module's
                # same-named module-level function)
                cands = [k for k in by_name.get(m, []) if k[0] != ""]
                if len(cands) == 1:
                    f.calls.add(cands[0])

    def _mark_worker_set(self):
        frontier: List[_Func] = []
        for cls, name in WORKER_ENTRIES:
            for f in self.methods.get((cls, name), []):
                f.worker = True
                frontier.append(f)
        while frontier:
            f = frontier.pop()
            for key in f.calls:
                for g in self.methods.get(key, []):
                    if not g.worker:
                        g.worker = True
                        frontier.append(g)

    # ---- lock regions ----------------------------------------------------

    def _lock_identity(self, f: _Func, env, ctx) -> Optional[Tuple[str, str]]:
        if isinstance(ctx, ast.Attribute) and re.search(r"lock", ctx.attr, re.I):
            owner = self._expr_type(f, env, ctx.value)
            return (owner or f"<module:{f.module}>", ctx.attr)
        if isinstance(ctx, ast.Name) and re.search(r"lock", ctx.id, re.I):
            return (f"<module:{f.module}>", ctx.id)
        return None

    def _lock_level(self, identity: Tuple[str, str], module: str) -> int:
        owner = identity[0]
        if owner in LOCK_LEVELS:
            return LOCK_LEVELS[owner]
        for suffix, level in MODULE_LOCK_LEVELS.items():
            if module.endswith(suffix):
                return level
        return 1

    def _lock_regions(self) -> List[_LockRegion]:
        out = []
        for f in self.funcs:
            env = self._envs[id(f)]
            for n in ast.walk(f.node):
                if not isinstance(n, ast.With):
                    continue
                for item in n.items:
                    ident = self._lock_identity(f, env, item.context_expr)
                    if ident is None:
                        continue
                    out.append(_LockRegion(
                        func=f, identity=ident,
                        level=self._lock_level(ident, f.module),
                        lineno=n.lineno,
                        end_lineno=getattr(n, "end_lineno", n.lineno),
                        node=n,
                    ))
        return out

    # ---- emission --------------------------------------------------------

    def _emit(self, f: _Func, node, rule, severity, message, key):
        line = getattr(node, "lineno", 0)
        mod: _Module = f._mod  # type: ignore[attr-defined]
        if mod.suppressed(line, rule, getattr(f.node, "lineno", None)):
            return
        self.findings.append(Finding(
            rule=rule, severity=severity,
            location=f"{f.module}:{line}", message=message, key=key,
        ))

    # ---- CONC601: shared-mutable-state census ----------------------------

    def _write_sites(self, f: _Func):
        """Yield (node, owner, attr) for attribute/container writes in f's
        own body (nested defs are their own functions)."""
        env = self._envs[id(f)]
        mod: _Module = f._mod  # type: ignore[attr-defined]
        declared_global: Set[str] = set()
        nested = set()
        for n in ast.walk(f.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not f.node:
                for x in ast.walk(n):
                    nested.add(id(x))
                nested.discard(id(n))

        def owner_of(expr) -> Optional[str]:
            return self._expr_type(f, env, expr)

        def classify_target(t):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from classify_target(e)
                return
            if isinstance(t, ast.Attribute):
                yield t, owner_of(t.value), t.attr
            elif isinstance(t, ast.Subscript):
                v = t.value
                if isinstance(v, ast.Attribute):
                    yield t, owner_of(v.value), v.attr
                elif isinstance(v, ast.Name):
                    if v.id in mod.module_globals:
                        yield t, "<module>", v.id
                    elif v.id in env or v.id in VAR_NAME_HINTS:
                        tname = env.get(v.id) or VAR_NAME_HINTS.get(v.id)
                        if tname in self.class_bases or tname in REPLICA_OWNED | ROUTER_OWNED | SHARED:
                            yield t, tname, "<subscript>"
                    # plain local container: thread-private, skip
            elif isinstance(t, ast.Name):
                if t.id in declared_global:
                    yield t, "<module>", t.id

        for n in ast.walk(f.node):
            if isinstance(n, ast.Global):
                declared_global.update(n.names)
        for n in ast.walk(f.node):
            if id(n) in nested:
                continue
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    if t is None:
                        continue
                    yield from classify_target(t)
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr not in MUTATORS:
                    continue
                recv = n.func.value
                # drill through dict.setdefault(...).append(...) chains
                if (
                    isinstance(recv, ast.Call)
                    and isinstance(recv.func, ast.Attribute)
                    and recv.func.attr in ("setdefault", "get")
                ):
                    recv = recv.func.value
                if isinstance(recv, ast.Attribute):
                    yield n, owner_of(recv.value), recv.attr
                elif isinstance(recv, ast.Name):
                    if recv.id in mod.module_globals:
                        yield n, "<module>", recv.id
                    # local container (rows.sort(...)): thread-private, skip

    def rule_census(self, regions: List[_LockRegion]):
        by_func_regions: Dict[int, List[_LockRegion]] = {}
        for r in regions:
            by_func_regions.setdefault(id(r.func), []).append(r)
        for f in self.funcs:
            f_regions = by_func_regions.get(id(f), [])
            for node, owner, attr in self._write_sites(f):
                line = getattr(node, "lineno", 0)
                locked = any(r.lineno <= line <= r.end_lineno for r in f_regions)
                cls = self._classify(f, owner, attr, locked)
                if cls is None:
                    self._emit(
                        f, node, "CONC601", SEV_ERROR,
                        f"unclassified shared write `{owner}.{attr}` in "
                        f"`{f.qual}`: "
                        + self._why_unclassified(f, owner)
                        + " — protect it with a lock, move it off the "
                        "worker path, or teach the analyzer its owner "
                        "(docs/STATIC_ANALYSIS.md \"Concurrency audit\")",
                        key=f"{f.module}::{owner}.{attr}::unclassified",
                    )
                else:
                    self._emit(
                        f, node, "CONC601", SEV_WARNING,
                        f"write census: `{owner}.{attr}` in `{f.qual}` "
                        f"[{cls}]",
                        key=f"{f.module}::{owner}.{attr}::{cls}",
                    )

    def _why_unclassified(self, f: _Func, owner) -> str:
        if owner is None:
            return ("the write target's owner cannot be resolved, so its "
                    "thread-confinement cannot be proven")
        if owner == "<module>":
            return ("module-global state mutated on a replica step thread "
                    "without a lock")
        if owner in SHARED:
            return ("state shared across replica threads written on a "
                    "worker-reachable path without a lock")
        if owner in ROUTER_OWNED:
            return ("router-thread-owned state written on a worker-reachable "
                    "path (the router thread owns placement/failover state)")
        return "ownership class is not in the analyzer's model"

    def _classify(self, f: _Func, owner, attr, locked: bool) -> Optional[str]:
        if locked:
            return "lock-protected"
        if owner is None:
            return None
        if owner == "<module>":
            return None if f.worker else "router-thread"
        init_confined = (
            f.name in ("__init__", "__post_init__")
            and f.cls
            and owner in self._hierarchy(f.cls)
        )
        if init_confined:
            return "init-confined"
        if owner in SHARED:
            return None if f.worker else "router-thread"
        if owner in ROUTER_OWNED:
            return None if f.worker else "router-thread"
        if owner in REPLICA_OWNED:
            return "replica-step-confined" if f.worker else "router-thread"
        return None

    # ---- CONC602: lock discipline ----------------------------------------

    def rule_lock_discipline(self, regions: List[_LockRegion]):
        # (a) explicit acquire()/release() anywhere
        for f in self.funcs:
            for n in ast.walk(f.node):
                if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)):
                    continue
                if n.func.attr in ("acquire", "release") and isinstance(
                    n.func.value, (ast.Attribute, ast.Name)
                ):
                    name = (
                        n.func.value.attr
                        if isinstance(n.func.value, ast.Attribute)
                        else n.func.value.id
                    )
                    if re.search(r"lock", name, re.I):
                        self._emit(
                            f, n, "CONC602", SEV_ERROR,
                            f"`{name}.{n.func.attr}()` in `{f.qual}` — locks "
                            f"are acquired only via `with` (an exception "
                            f"between acquire and release leaks the lock "
                            f"and wedges every replica thread)",
                            key=f"{f.module}::acquire-release",
                        )
        # (b) ordering + re-entry + (c) blocking under the router lock,
        # over the traced call graph
        for r in regions:
            reach = self._reachable_from_region(r)
            # direct nested with-regions in the same function
            inner = [
                r2 for r2 in regions
                if r2 is not r and r2.func is r.func
                and r.lineno <= r2.lineno <= r.end_lineno
            ]
            inner += [r2 for r2 in regions if id(r2.func) in reach and r2.func is not r.func]
            for r2 in inner:
                if r2.identity == r.identity:
                    if self.lock_kinds.get(r.identity, "lock") != "rlock":
                        self._emit(
                            r.func, r.node, "CONC602", SEV_ERROR,
                            f"re-entrant acquisition of non-reentrant lock "
                            f"`{r.identity[0]}.{r.identity[1]}` (held at "
                            f"{r.func.qual}:{r.lineno}, re-acquired at "
                            f"{r2.func.qual}:{r2.lineno}) — deadlock; use "
                            f"threading.RLock or restructure",
                            key=f"{r.func.module}::lock-reentry",
                        )
                elif r2.level <= r.level:
                    self._emit(
                        r.func, r.node, "CONC602", SEV_ERROR,
                        f"lock-order violation: holding level-{r.level} "
                        f"`{r.identity[0]}.{r.identity[1]}` "
                        f"({r.func.qual}:{r.lineno}) can acquire "
                        f"level-{r2.level} `{r2.identity[0]}.{r2.identity[1]}` "
                        f"({r2.func.qual}:{r2.lineno}) — the global order is "
                        f"router(0) -> replica(1) -> telemetry session(2) -> "
                        f"registry(3) -> family(4) -> instrument(5), "
                        f"strictly increasing (cycle risk)",
                        key=f"{r.func.module}::lock-order",
                    )
            if r.level == 0:
                self._check_blocking(r, reach)

    def _reachable_from_region(self, r: _LockRegion) -> Set[int]:
        """ids of functions transitively callable from inside the region."""
        start: Set[Tuple[str, str]] = set()
        for n in ast.walk(r.node):
            if not isinstance(n, ast.Call):
                continue
            fn = n.func
            f = r.func
            env = self._envs[id(f)]
            if isinstance(fn, ast.Name) and ("", fn.id) in self.methods:
                start.add(("", fn.id))
            elif isinstance(fn, ast.Attribute):
                recv = fn.value
                if isinstance(recv, ast.Name) and recv.id == "self" and f.cls:
                    for c in self._hierarchy(f.cls):
                        if (c, fn.attr) in self.methods:
                            start.add((c, fn.attr))
                else:
                    t = self._expr_type(f, env, recv)
                    if t:
                        for c in self._hierarchy(t):
                            if (c, fn.attr) in self.methods:
                                start.add((c, fn.attr))
        seen: Set[int] = set()
        frontier: List[_Func] = []
        for key in start:
            for g in self.methods.get(key, []):
                if id(g) not in seen:
                    seen.add(id(g))
                    frontier.append(g)
        while frontier:
            g = frontier.pop()
            for key in g.calls:
                for h in self.methods.get(key, []):
                    if id(h) not in seen:
                        seen.add(id(h))
                        frontier.append(h)
        return seen

    def _check_blocking(self, r: _LockRegion, reach: Set[int]):
        funcs = [f for f in self.funcs if id(f) in reach]
        scopes = [(r.func, r.node)] + [(g, g.node) for g in funcs]
        for g, scope in scopes:
            for n in ast.walk(scope):
                if not isinstance(n, ast.Call):
                    continue
                fn = n.func
                name = None
                if isinstance(fn, ast.Attribute) and fn.attr in BLOCKING_ATTRS:
                    name = fn.attr
                elif isinstance(fn, ast.Name) and fn.id in BLOCKING_NAMES:
                    name = fn.id
                if not name:
                    continue
                self._emit(
                    g, n, "CONC602", SEV_ERROR,
                    f"blocking call `{name}(...)` reachable while holding "
                    f"router-level lock `{r.identity[0]}.{r.identity[1]}` "
                    f"(acquired {r.func.qual}:{r.lineno}) — a block under "
                    f"the router lock stalls every replica; fetch/sleep/IO "
                    f"outside it",
                    key=f"{r.func.module}::blocking-under-router-lock",
                )

    # ---- CONC603: telemetry atomicity ------------------------------------

    def rule_instrument_atomicity(self, regions: List[_LockRegion]):
        by_func_regions: Dict[int, List[_LockRegion]] = {}
        for r in regions:
            by_func_regions.setdefault(id(r.func), []).append(r)
        for f in self.funcs:
            in_metrics = f.module.endswith("telemetry/metrics.py")
            inside_instrument = in_metrics and f.cls in INSTRUMENT_CLASSES
            f_regions = by_func_regions.get(id(f), [])
            for n in ast.walk(f.node):
                if not isinstance(n, (ast.Assign, ast.AugAssign)):
                    continue
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    hit = None
                    if isinstance(t, ast.Attribute) and t.attr in INSTRUMENT_INTERNALS:
                        hit = t.attr
                    elif isinstance(t, ast.Subscript) and isinstance(
                        t.value, ast.Attribute
                    ) and t.value.attr in INSTRUMENT_BUCKETS:
                        hit = t.value.attr
                    if hit is None:
                        continue
                    line = getattr(n, "lineno", 0)
                    locked = any(
                        r.lineno <= line <= r.end_lineno for r in f_regions
                    )
                    if inside_instrument and (locked or f.name == "__init__"):
                        continue  # the atomic mutator itself
                    self._emit(
                        f, n, "CONC603", SEV_ERROR,
                        f"read-modify-write on instrument internal "
                        f"`.{hit}` in `{f.qual}` — metric mutations must go "
                        f"through the registry's atomic inc()/set()/"
                        f"observe() (a bare `+=` from a replica thread "
                        f"loses updates; the GIL does not make it atomic)",
                        key=f"{f.module}::instrument-internals",
                    )

    # ---- CONC604: router -> session touch census -------------------------

    def rule_session_touches(self):
        for f in self.funcs:
            if f.cls != "ServingRouter" or not f.module.endswith(
                "runtime/router.py"
            ):
                continue
            parents: Dict[int, ast.AST] = {}
            for n in ast.walk(f.node):
                for child in ast.iter_child_nodes(n):
                    parents[id(child)] = n
            for n in ast.walk(f.node):
                if not (isinstance(n, ast.Attribute) and n.attr == "session"):
                    continue
                p = parents.get(id(n))
                touched = None
                if isinstance(p, ast.Attribute) and p.value is n:
                    touched = p.attr
                if touched is None:
                    self._emit(
                        f, n, "CONC604", SEV_WARNING,
                        f"router touch census: bare `session` reference in "
                        f"`{f.qual}`",
                        key=f"{f.module}::session.<bare>",
                    )
                    continue
                if touched in DEVICE_STATE_ATTRS:
                    self._emit(
                        f, n, "CONC604", SEV_ERROR,
                        f"ServingRouter.{f.name} touches replica device "
                        f"state `session.{touched}` — the router may only "
                        f"read committed host-side snapshots; device state "
                        f"belongs to the replica's confinement set "
                        f"(session + handle + worker)",
                        key=f"{f.module}::session.{touched}::device-state",
                    )
                    continue
                if touched == "app":
                    gp = parents.get(id(p))
                    sub = gp.attr if (
                        isinstance(gp, ast.Attribute) and gp.value is p
                    ) else None
                    if sub != "config":
                        self._emit(
                            f, n, "CONC604", SEV_ERROR,
                            f"ServingRouter.{f.name} reaches "
                            f"`session.app.{sub or '<bare>'}` — only the "
                            f"frozen `session.app.config` read is a "
                            f"host-side snapshot; everything else on the "
                            f"app is replica device state",
                            key=f"{f.module}::session.app::device-state",
                        )
                        continue
                    touched = "app.config"
                self._emit(
                    f, n, "CONC604", SEV_WARNING,
                    f"router touch census: `session.{touched}` read in "
                    f"`{f.qual}` (host-side snapshot allowlist; a new "
                    f"entry here is reviewed like a new collective)",
                    key=f"{f.module}::session.{touched}",
                )

    # ---- driver ----------------------------------------------------------

    def run(self) -> List[Finding]:
        regions = self._lock_regions()
        self.rule_census(regions)
        self.rule_lock_discipline(regions)
        self.rule_instrument_atomicity(regions)
        self.rule_session_touches()
        self.findings.sort(key=lambda f: (f.rule, f.key, f.location))
        return self.findings


# ---------------------------------------------------------------------------
# entry points (mirrors graph/shard/memory audit shape)
# ---------------------------------------------------------------------------


def _scope_files(root: Optional[pathlib.Path] = None) -> List[Tuple[pathlib.Path, str]]:
    if root is None:
        root = pathlib.Path(__file__).resolve().parents[2]
    pkg = root / PACKAGE
    out = []
    for suffix in SCOPE_SUFFIXES:
        p = pkg / suffix
        if p.is_file():
            out.append((p, suffix))
    return out


def _match_scope(path: pathlib.Path) -> Optional[str]:
    s = str(path)
    for suffix in SCOPE_SUFFIXES:
        if s.endswith(suffix):
            return suffix
    # fixture fallback: match by basename so tmp-dir snippets audit as the
    # file they stand in for
    for suffix in SCOPE_SUFFIXES:
        if path.name == pathlib.Path(suffix).name:
            return suffix
    return None


def audit_paths(paths: List[pathlib.Path]) -> List[Finding]:
    """Audit arbitrary snippet files (test fixtures): each file is scoped by
    suffix/basename match against :data:`SCOPE_SUFFIXES` and the RAW
    findings (census entries included, no baseline filtering) come back."""
    files = []
    for p in paths:
        rel = _match_scope(p)
        if rel is None:
            raise ValueError(
                f"{p}: not a recognizable scope file (expected one of "
                f"{SCOPE_SUFFIXES} by suffix or basename)"
            )
        files.append((p, rel))
    return _Analyzer(files).run()


def _build_report(findings: List[Finding]) -> Dict:
    classifications: Dict[str, int] = {}
    census: Dict[str, int] = {}
    touches: Dict[str, int] = {}
    errors = 0
    for f in findings:
        if f.severity == SEV_ERROR:
            errors += 1
            continue
        if f.rule == "CONC601":
            cls = f.key.rsplit("::", 1)[-1]
            classifications[cls] = classifications.get(cls, 0) + 1
            census[f.key] = census.get(f.key, 0) + 1
        elif f.rule == "CONC604":
            touches[f.key] = touches.get(f.key, 0) + 1
    return {
        "write_sites": sum(classifications.values()),
        "classifications": dict(sorted(classifications.items())),
        "errors": errors,
        "census": dict(sorted(census.items())),
        "session_touches": dict(sorted(touches.items())),
        "worker_entries": [f"{c}.{m}" for c, m in WORKER_ENTRIES],
    }


def last_report() -> Dict:
    return _LAST_REPORT


def render_breakdown(report: Optional[Dict] = None) -> str:
    rep = report if report is not None else _LAST_REPORT
    if not rep:
        return ""
    lines = [
        "concurrency write-site census "
        f"({rep['write_sites']} classified sites; worker entries: "
        f"{', '.join(rep['worker_entries'])}):"
    ]
    for cls, n in rep["classifications"].items():
        lines.append(f"  {cls:>22}: {n}")
    if rep["session_touches"]:
        lines.append(
            "router->session host-snapshot touches: "
            + ", ".join(
                k.split("::", 1)[1] for k in rep["session_touches"]
            )
        )
    return "\n".join(lines)


def run(write_baseline: bool = False) -> List[Finding]:
    """Audit the real tree against ``conc_baseline.json``; returns the NEW
    (gate-failing) findings. Errors (unclassified/shared/ordering/device-
    state findings) are never baselined — only the classified census and
    the router->session touch allowlist are."""
    global _LAST_REPORT
    findings = _Analyzer(_scope_files()).run()
    _LAST_REPORT = _build_report(findings)
    warnings = [f for f in findings if f.severity == SEV_WARNING]
    errors = [f for f in findings if f.severity == SEV_ERROR]
    if write_baseline:
        Baseline.from_findings(warnings).save(BASELINE_PATH)
        return errors
    return Baseline.load(BASELINE_PATH).filter_new(warnings) + errors
