"""Registry of every Pallas kernel in ``ops/`` for the kernel audit (KERN70x).

One :class:`KernelSpec` per ``pl.pallas_call`` site. Instead of hand-mirroring
each kernel's grid/BlockSpec/scratch layout (which would drift the moment a
kernel changes), the registry TRACES the real entry point with
``jax.make_jaxpr`` at the committed bench shapes and reads the truth off the
``pallas_call`` equation's ``grid_mapping``:

- ``grid_mapping.grid`` — the launch grid;
- ``grid_mapping.block_mappings`` — one per tensor operand/output (scalar-
  prefetch operands ride SMEM and are excluded), each carrying
  ``block_shape`` and ``array_shape_dtype``;
- the kernel jaxpr's trailing invars — the ``pltpu.VMEM`` scratch avals.

Tracing is abstract (ShapeDtypeStruct args, no compile, no devices), so the
whole census runs on a CPU-only host in seconds. Tile candidates are
injected through :func:`ops.tile_defaults.tile_overrides` — the same lookup
path the kernels use for their committed defaults — so a candidate exercises
exactly the code a user would hit by editing ``tuning_table.json``.

Each spec also names the kernel's NATIVE FALLBACK and the tests that must
reference it (KERN703): a new kernel cannot ship unregistered (the audit
AST-scans ``ops/`` for unclaimed ``pallas_call`` sites) or unreferenced
(fallback must import, parity/lowering test files must mention the entry).
"""

from __future__ import annotations

import ast
import functools
import pathlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

OPS_DIR = pathlib.Path(__file__).resolve().parent.parent / "ops"
REPO_ROOT = OPS_DIR.parent.parent

#: committed 1B/8B attention shapes (device_model.LLAMA_1B / LLAMA_8B and
#: the BENCH_ROW_MODELS kv buckets) — literal here so a registry import
#: cannot recurse into the traced-suite modules
_1B = dict(H=2048, I=8192, Hq=32, Hkv=8, D=64, L=16)
_8B = dict(H=4096, I=14336, Hq=32, Hkv=8, D=128, L=32)


@dataclass(frozen=True)
class KernelCase:
    """One committed (shape-class, dtype) instantiation of a kernel."""

    shape_class: str
    dtype: str  # census label AND the tuning-table dtype key
    build: Callable[[], Tuple[Callable, tuple]]  # -> (fn, abstract args)


@dataclass(frozen=True)
class KernelSpec:
    name: str
    site: Tuple[str, str]  # (ops file, enclosing function of the pallas_call)
    entry: str  # public entry point name (test files must mention it)
    fallback: str  # "dotted.module:attr" native path
    parity_test: str  # repo-relative test file exercising kernel vs fallback
    cases: Tuple[KernelCase, ...]
    lowering_test: str = "tests/test_tpu_lowering.py"
    tile_params: Tuple[str, ...] = ()  # free tile params read from the table
    sweep: Tuple[Tuple[str, Tuple[int, ...]], ...] = ()  # param -> candidates
    table_kernel: Optional[str] = None  # tuning-table key (defaults to name)

    @property
    def table_key(self) -> str:
        return self.table_kernel or self.name


@dataclass
class BlockInfo:
    role: str  # "in" | "out"
    block_shape: Tuple[int, ...]
    array_shape: Tuple[int, ...]
    dtype: str
    itemsize: int


@dataclass
class KernelInstance:
    kernel: str
    shape_class: str
    dtype: str
    tiles: Dict[str, int]  # the resolved tile params (empty if none)
    grid: Tuple[int, ...]
    blocks: List[BlockInfo]
    scratch: List[Tuple[Tuple[int, ...], str, int]]  # (shape, dtype, bytes)
    flops_per_step: int
    dot_stats: List[Tuple[int, int, int]]  # (flops, contract_depth, out_lanes)

    @property
    def key(self) -> str:
        return f"{self.kernel}/{self.shape_class}/{self.dtype}"

    @property
    def scratch_bytes(self) -> int:
        return sum(b for _, _, b in self.scratch)

    @property
    def block_bytes_single(self) -> int:
        """One copy of every operand/output window (the per-step DMA set)."""
        out = 0
        for b in self.blocks:
            n = 1
            for d in b.block_shape:
                n *= d
            out += n * b.itemsize
        return out

    @property
    def vmem_bytes(self) -> int:
        """Static VMEM model (KERN701): every blocked operand/output window
        is double-buffered by the Pallas pipeline; scratch is single."""
        return 2 * self.block_bytes_single + self.scratch_bytes


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _unjit(fn):
    """The unjitted callable behind a ``jax.jit`` wrapper — tracing through
    the wrapper would let jit's trace cache return a stale jaxpr when only a
    tile override (invisible to the cache key) changed."""
    return getattr(fn, "__wrapped__", fn)


# ---------------------------------------------------------------------------
# case builders (committed bench shapes)
# ---------------------------------------------------------------------------


def _flash_case(S, dtype, *, window=None, packed=False):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import flash_attention as fa

        dt = jnp.dtype(dtype)
        m = _1B
        q = _sds((1, m["Hq"], S, m["D"]), dt)
        valid = _sds((1, S), jnp.int32)
        fn = functools.partial(
            _unjit(fa.flash_attention_bhsd),
            scale=m["D"] ** -0.5, causal=True, window=window, packed=packed,
        )
        return fn, (q, q, q, valid)

    return build


def _tkg_case(B, bucket, model, cache_dtype):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import decode_attention as da

        m = model
        q = _sds((B, 1, m["Hq"], m["D"]), jnp.bfloat16)
        cache = _sds((m["L"], B, bucket, m["Hkv"], m["D"]), jnp.dtype(cache_dtype))
        li = _sds((), jnp.int32)
        mask = _sds((B, 1, 1, bucket), jnp.bool_)
        fn = functools.partial(
            _unjit(da.tkg_decode_attention), scale=m["D"] ** -0.5, n_kv=m["Hkv"]
        )
        return fn, (q, cache, cache, li, mask)

    return build


def _paged_tkg_case(B, MB, bs, cache_dtype):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import decode_attention as da

        m = _1B
        q = _sds((B, 1, m["Hq"], m["D"]), jnp.bfloat16)
        cache = _sds((m["L"], 65, m["Hkv"], bs, m["D"]), jnp.dtype(cache_dtype))
        li = _sds((), jnp.int32)
        bt = _sds((B, MB), jnp.int32)
        mask = _sds((B, 1, 1, MB * bs), jnp.bool_)
        fn = functools.partial(
            _unjit(da.paged_tkg_decode_attention),
            scale=m["D"] ** -0.5, n_kv=m["Hkv"],
        )
        return fn, (q, cache, cache, li, bt, mask)

    return build


def _paged_flash_case(Sq, MB, bs, cache_dtype):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import paged_flash_attention as pf

        m = _1B
        quant = jnp.dtype(cache_dtype) == jnp.int8
        q = _sds((1, Sq, m["Hq"], m["D"]), jnp.bfloat16)
        cache = _sds((65, m["Hkv"], bs, m["D"]), jnp.dtype(cache_dtype))
        bt = _sds((1, MB), jnp.int32)
        pos = _sds((1, Sq), jnp.int32)
        lim = _sds((1,), jnp.int32)
        raw = _unjit(pf.paged_flash_attention)
        kw = dict(scale=m["D"] ** -0.5, n_rep=m["Hq"] // m["Hkv"])
        if quant:
            scale = _sds((m["Hkv"],), jnp.float32)

            def fn(q, k, v, bt, pos, lim, ks, vs):
                return raw(q, k, v, bt, pos, lim, k_scale=ks, v_scale=vs, **kw)

            return fn, (q, cache, cache, bt, pos, lim, scale, scale)
        return functools.partial(raw, **kw), (q, cache, cache, bt, pos, lim)

    return build


def _ragged_case(T, R, MB, bs, cache_dtype):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import ragged_paged_attention as rp

        m = _1B
        quant = jnp.dtype(cache_dtype) == jnp.int8
        q = _sds((T, m["Hq"], m["D"]), jnp.bfloat16)
        cache = _sds((65, m["Hkv"], bs, m["D"]), jnp.dtype(cache_dtype))
        bt = _sds((R, MB), jnp.int32)
        row = _sds((R,), jnp.int32)
        raw = _unjit(rp.ragged_paged_attention)
        kw = dict(scale=m["D"] ** -0.5, n_rep=m["Hq"] // m["Hkv"])
        if quant:
            scale = _sds((m["Hkv"],), jnp.float32)

            def fn(q, k, v, bt, rs, rl, cl, ks, vs):
                return raw(q, k, v, bt, rs, rl, cl, k_scale=ks, v_scale=vs, **kw)

            return fn, (q, cache, cache, bt, row, row, row, scale, scale)
        return functools.partial(raw, **kw), (q, cache, cache, bt, row, row, row)

    return build


def _fused_attn_case(B, bucket):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import decode_block as db

        m = _1B
        H, Hq, Hkv, D, L = m["H"], m["Hq"], m["Hkv"], m["D"], m["L"]
        N3 = (Hq + 2 * Hkv) * D
        x = _sds((B, 1, H), jnp.bfloat16)
        gamma = _sds((H,), jnp.bfloat16)
        wqkv = _sds((H, N3), jnp.bfloat16)
        wout = _sds((Hq * D, H), jnp.bfloat16)
        cs = _sds((B, 1, D // 2), jnp.float32)
        cache = _sds((L, B, bucket, Hkv, D), jnp.bfloat16)
        li = _sds((), jnp.int32)
        slots = _sds((B,), jnp.int32)
        mask = _sds((B, 1, 1, bucket), jnp.bool_)
        pos = _sds((B, 1), jnp.int32)
        fn = functools.partial(
            _unjit(db.fused_attn_block),
            scale=D ** -0.5, eps=1e-5, n_kv=Hkv,
        )
        return fn, (x, gamma, wqkv, wout, cs, cs, cache, cache, li, slots,
                    mask, pos)

    return build


def _fused_mlp_case(B):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import decode_block as db

        m = _1B
        H, I = m["H"], m["I"]
        x = _sds((B, 1, H), jnp.bfloat16)
        gamma = _sds((H,), jnp.bfloat16)
        wg = _sds((H, I), jnp.bfloat16)
        wd = _sds((I, H), jnp.bfloat16)
        fn = functools.partial(_unjit(db.fused_mlp_block), eps=1e-5)
        return fn, (x, gamma, wg, wg, wd)

    return build


def _qmm_case(B, model):
    """Decode-shaped int4 fused-dequant matmul at the model's widest linear
    (the H -> I up/gate projection — the weight-read roofline term)."""

    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import quant_matmul as qm

        m = model
        K, N = m["H"], m["I"]
        span = 2 * qm.INT4_GROUP
        Kp = -(-K // span) * span
        x = _sds((B, K), jnp.bfloat16)
        w = _sds((Kp // 2, N), jnp.uint8)
        s = _sds((Kp // qm.INT4_GROUP, N), jnp.float32)
        return _unjit(qm.quant_matmul), (x, w, s)

    return build


def _moe_case(T, k, E):
    def build():
        import jax.numpy as jnp

        from neuronx_distributed_inference_tpu.ops import moe_decode as md

        m = _1B
        H, I = m["H"], m["I"]
        x = _sds((T, H), jnp.bfloat16)
        idx = _sds((T, k), jnp.int32)
        w = _sds((T, k), jnp.float32)
        wg = _sds((E, H, I), jnp.bfloat16)
        wd = _sds((E, I, H), jnp.bfloat16)
        fn = _unjit(md.fused_moe_decode)
        return fn, (x, idx, w, wg, wg, wd)

    return build


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

_ATTN = "neuronx_distributed_inference_tpu.modules.attention"

REGISTRY: Tuple[KernelSpec, ...] = (
    KernelSpec(
        name="flash_attention",
        site=("flash_attention.py", "flash_attention_bhsd"),
        entry="flash_attention_bhsd",
        fallback=f"{_ATTN}:_masked_softmax_attention",
        parity_test="tests/test_flash_attention.py",
        tile_params=("bq", "bkv"),
        sweep=(("bq", (128, 256, 512)), ("bkv", (128, 256, 512))),
        cases=(
            KernelCase("plain", "bfloat16", _flash_case(8192, "bfloat16")),
            KernelCase("plain", "float32", _flash_case(512, "float32")),
            KernelCase(
                "masked", "bfloat16", _flash_case(8192, "bfloat16", window=128)
            ),
        ),
    ),
    KernelSpec(
        name="flash_attention_packed",
        site=("flash_attention.py", "_packed_flash_call"),
        entry="flash_attention_bhsd",
        fallback=f"{_ATTN}:_masked_softmax_attention",
        parity_test="tests/test_flash_attention.py",
        tile_params=("bq", "bkv"),
        table_kernel="flash_attention",  # shares the unpacked tile rule
        cases=(
            KernelCase(
                "plain", "bfloat16", _flash_case(8192, "bfloat16", packed=True)
            ),
        ),
    ),
    KernelSpec(
        name="tkg_decode_attention",
        site=("decode_attention.py", "_common_call"),
        entry="tkg_decode_attention",
        fallback=f"{_ATTN}:attention_decode",
        parity_test="tests/test_decode_attention.py",
        tile_params=("bs",),
        sweep=(("bs", (128, 256, 512, 1024)),),
        cases=(
            KernelCase("kv512", "bfloat16", _tkg_case(1, 512, _1B, "bfloat16")),
            KernelCase("kv512", "int8", _tkg_case(1, 512, _1B, "int8")),
            KernelCase("kv1024", "bfloat16", _tkg_case(8, 1024, _1B, "bfloat16")),
            KernelCase(
                "kv16896", "bfloat16", _tkg_case(1, 16896, _1B, "bfloat16")
            ),
            KernelCase("kv512", "int8_8b", _tkg_case(1, 512, _8B, "int8")),
        ),
    ),
    KernelSpec(
        name="paged_tkg_decode_attention",
        site=("decode_attention.py", "_common_call"),
        entry="paged_tkg_decode_attention",
        fallback=f"{_ATTN}:attention_decode",
        parity_test="tests/test_decode_attention.py",
        # no free tile: the kv tile IS the paged-cache block size, a cache-
        # layout decision owned by the serving config, not the tuning table
        cases=(
            KernelCase(
                "kv1024", "bfloat16", _paged_tkg_case(8, 8, 128, "bfloat16")
            ),
            KernelCase("kv1024", "int8", _paged_tkg_case(8, 8, 128, "int8")),
        ),
    ),
    KernelSpec(
        name="paged_flash_attention",
        site=("paged_flash_attention.py", "paged_flash_attention"),
        entry="paged_flash_attention",
        fallback=f"{_ATTN}:attention_decode",
        parity_test="tests/test_chunked_prefill.py",
        tile_params=("tq",),
        sweep=(("tq", (64, 128, 256, 512)),),
        cases=(
            KernelCase(
                "sq512", "bfloat16", _paged_flash_case(512, 16, 128, "bfloat16")
            ),
            KernelCase("sq512", "int8", _paged_flash_case(512, 16, 128, "int8")),
        ),
    ),
    KernelSpec(
        name="ragged_paged_attention",
        site=("ragged_paged_attention.py", "ragged_paged_attention"),
        entry="ragged_paged_attention",
        fallback=(
            "neuronx_distributed_inference_tpu.ops.ragged_paged_attention"
            ":ragged_attention_native"
        ),
        parity_test="tests/test_ragged_attention.py",
        tile_params=("tq",),
        sweep=(("tq", (8, 16, 32)),),
        cases=(
            KernelCase(
                "mixed", "bfloat16", _ragged_case(512, 8, 16, 128, "bfloat16")
            ),
            KernelCase("mixed", "int8", _ragged_case(512, 8, 16, 128, "int8")),
        ),
    ),
    KernelSpec(
        name="fused_attn_block",
        site=("decode_block.py", "fused_attn_block"),
        entry="fused_attn_block",
        fallback="neuronx_distributed_inference_tpu.models.base:decoder_layer",
        parity_test="tests/test_decode_block.py",
        tile_params=("ta_cap", "tc_cap", "bs"),
        sweep=(
            ("ta_cap", (128, 256, 512)),
            ("tc_cap", (256, 512)),
            ("bs", (512,)),
        ),
        cases=(KernelCase("h2048", "bfloat16", _fused_attn_case(4, 512)),),
    ),
    KernelSpec(
        name="fused_mlp_block",
        site=("decode_block.py", "fused_mlp_block"),
        entry="fused_mlp_block",
        fallback="neuronx_distributed_inference_tpu.models.base:_decoder_layer_mlp",
        parity_test="tests/test_decode_block.py",
        tile_params=("ti_cap",),
        sweep=(("ti_cap", (128, 256, 512, 1024)),),
        cases=(KernelCase("i8192", "bfloat16", _fused_mlp_case(4)),),
    ),
    KernelSpec(
        name="fused_moe_decode",
        site=("moe_decode.py", "fused_moe_decode"),
        entry="fused_moe_decode",
        fallback="neuronx_distributed_inference_tpu.modules.moe:expert_mlps_dense",
        parity_test="tests/test_moe_dispatch.py",
        tile_params=("ti_cap",),
        sweep=(("ti_cap", (128, 256, 512)),),
        cases=(KernelCase("h2048_i8192", "bfloat16", _moe_case(4, 2, 8)),),
    ),
    KernelSpec(
        name="quant_matmul",
        site=("quant_matmul.py", "quant_matmul"),
        entry="quant_matmul",
        fallback=(
            "neuronx_distributed_inference_tpu.ops.quant_matmul"
            ":int4_matmul_native"
        ),
        parity_test="tests/test_quant_matmul.py",
        tile_params=("bn",),
        sweep=(("bn", (128, 256, 512)),),
        cases=(
            KernelCase("k2048_n8192", "bfloat16", _qmm_case(8, _1B)),
            KernelCase("k4096_n14336", "bfloat16", _qmm_case(8, _8B)),
        ),
    ),
)


#: in-code fallback tile constants per (table_kernel, param) — the values
#: the kernels pass as ``tile_default(..., fallback=...)``. KERN704 pins
#: hand_picked table entries to these, so the table and the code cannot
#: silently disagree about today's defaults.
HAND_PICKED: Dict[str, Dict[str, Dict[str, int]]] = {
    "flash_attention": {
        "plain": {"bq": 512, "bkv": 512},
        "masked": {"bq": 128, "bkv": 128},
    },
    "tkg_decode_attention": {"*": {"bs": 512}},
    "paged_flash_attention": {"*": {"tq": 128}},
    "ragged_paged_attention": {"*": {"tq": 16}},
    "fused_attn_block": {"*": {"ta_cap": 256, "tc_cap": 512, "bs": 512}},
    "fused_mlp_block": {"*": {"ti_cap": 512}},
    "fused_moe_decode": {"*": {"ti_cap": 512}},
    "quant_matmul": {"*": {"bn": 256}},
}


def hand_picked_tiles(table_kernel: str, shape_class: str) -> Optional[Dict[str, int]]:
    per = HAND_PICKED.get(table_kernel)
    if per is None:
        return None
    return per.get(shape_class, per.get("*"))


# ---------------------------------------------------------------------------
# trace-based extraction
# ---------------------------------------------------------------------------


def _sub_jaxprs(eqn):
    """Every sub-jaxpr an equation carries — including tuple-valued params
    (``cond``'s ``branches``)."""
    import jax.core as jcore

    out = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if isinstance(x, jcore.ClosedJaxpr):
                out.append(x.jaxpr)
            elif isinstance(x, jcore.Jaxpr):
                out.append(x)
    return out


def _find_pallas_eqns(jaxpr):
    hits = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            hits.append(eqn)
        for sub in _sub_jaxprs(eqn):
            hits.extend(_find_pallas_eqns(sub))
    return hits


def _dot_stats(jaxpr, out):
    """(flops, contraction_depth, out_lane_width) per dot_general, cond
    branches included (KERN705 MXU-occupancy input)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            (lc, _), _ = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            k = 1
            for ax in lc:
                k *= lhs.shape[ax]
            oshape = eqn.outvars[0].aval.shape
            n = 1
            for d in oshape:
                n *= d
            lanes = oshape[-1] if oshape else 1
            out.append((2 * n * k, k, lanes))
        for sub in _sub_jaxprs(eqn):
            _dot_stats(sub, out)
    return out


def instantiate(
    spec: KernelSpec, case: KernelCase, tiles: Optional[Dict[str, int]] = None
) -> KernelInstance:
    """Trace one committed case (optionally under tile overrides) and read
    the kernel's launch truth off the traced ``pallas_call`` equation."""
    import jax
    import numpy as np

    from neuronx_distributed_inference_tpu.analysis.cost_audit import jaxpr_flops
    from neuronx_distributed_inference_tpu.ops.tile_defaults import (
        table_entry,
        tile_overrides,
    )

    fn, args = case.build()
    if tiles:
        ctx = tile_overrides(spec.table_key, tiles)
    else:
        import contextlib

        ctx = contextlib.nullcontext()
    with ctx:
        jaxpr = jax.make_jaxpr(lambda *a: fn(*a))(*args)
    eqns = _find_pallas_eqns(jaxpr.jaxpr)
    if not eqns:
        raise RuntimeError(f"{spec.name}/{case.shape_class}: no pallas_call traced")
    eqn = eqns[0]
    gm = eqn.params["grid_mapping"]
    blocks: List[BlockInfo] = []
    for i, bm in enumerate(gm.block_mappings):
        sd = bm.array_shape_dtype
        blocks.append(
            BlockInfo(
                role="in" if i < gm.num_inputs else "out",
                block_shape=tuple(int(d) for d in bm.block_shape),
                array_shape=tuple(int(d) for d in sd.shape),
                dtype=str(sd.dtype),
                itemsize=int(np.dtype(sd.dtype).itemsize),
            )
        )
    kj = eqn.params["jaxpr"]
    scratch = []
    if gm.num_scratch_operands:
        for v in kj.invars[-gm.num_scratch_operands:]:
            shape = tuple(int(d) for d in v.aval.shape)
            n = 1
            for d in shape:
                n *= d
            scratch.append(
                (shape, str(v.aval.dtype), n * int(np.dtype(v.aval.dtype).itemsize))
            )
    resolved: Dict[str, int] = {}
    if tiles:
        resolved = dict(tiles)
    elif spec.tile_params:
        entry = table_entry(spec.table_key, case.shape_class, case.dtype) or {}
        hand = hand_picked_tiles(spec.table_key, case.shape_class) or {}
        for p in spec.tile_params:
            v = (entry.get("tiles") or {}).get(p, hand.get(p))
            if v is not None:
                resolved[p] = int(v)
    return KernelInstance(
        kernel=spec.name,
        shape_class=case.shape_class,
        dtype=case.dtype,
        tiles=resolved,
        grid=tuple(int(g) for g in gm.grid),
        blocks=blocks,
        scratch=scratch,
        flops_per_step=int(jaxpr_flops(kj)),
        dot_stats=_dot_stats(kj, []),
    )


@functools.lru_cache(maxsize=1)
def collect_instances() -> Tuple[KernelInstance, ...]:
    """Every registered kernel traced at its committed cases with the
    (table-routed) default tiles. Memoized: the suite, ``legal_tiles`` and
    the tests all share one trace pass."""
    out = []
    for spec in REGISTRY:
        for case in spec.cases:
            out.append(instantiate(spec, case))
    return tuple(out)


def reset_cache() -> None:
    collect_instances.cache_clear()


# ---------------------------------------------------------------------------
# AST census of pallas_call sites (KERN703's "no unregistered kernel")
# ---------------------------------------------------------------------------


def pallas_sites() -> List[Tuple[str, str, int]]:
    """Every ``pl.pallas_call`` call expression under ``ops/`` as
    (file, enclosing function, line)."""
    sites = []
    for path in sorted(OPS_DIR.glob("*.py")):
        tree = ast.parse(path.read_text())

        def walk(node, fn_name):
            for child in ast.iter_child_nodes(node):
                name = fn_name
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    name = child.name
                if isinstance(child, ast.Call):
                    f = child.func
                    callee = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
                    if callee == "pallas_call":
                        sites.append((path.name, fn_name or "<module>", child.lineno))
                walk(child, name)

        walk(tree, None)
    return sites
