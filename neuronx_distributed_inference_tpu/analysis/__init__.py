"""Static-analysis subsystem: graph-contract auditing for the AOT stack.

Cooperating passes, one finding/baseline format, one CLI
(``python -m neuronx_distributed_inference_tpu.analysis``, parser shared
with ``scripts/run_static_analysis.py`` via :mod:`.cli`):

- :mod:`.graph_audit` — jaxpr/HLO contract auditor: per sub-model tag ×
  bucket, collective census, dtype discipline, KV-cache donation, and
  bucket skeleton invariance (rules GRAPH2xx).
- :mod:`.shard_audit` — sharding-contract auditor: realized vs declared
  PartitionSpec per weight/cache leaf, no replicated cache, no in-loop
  weight gathers, pinned sharding census (rules GRAPH30x).
- :mod:`.memory_audit` — HBM memory contracts: the compiled
  ``input_output_alias`` table must alias every donated cache leaf, and a
  per-(phase, bucket) footprint model is pinned with a percentage
  regression gate (rules MEM40x).
- :mod:`.programs` — the shared harness that traces/lowers/compiles the
  tiny audit programs ONCE per process for all three graph-level suites.
- :mod:`.retrace_guard` — trace-time hooks + a context manager that fail
  steady-state recompiles after ``warmup()``.
- :mod:`.tpulint` — AST rules for host-sync/print/time under trace, Pallas
  ``interpret`` plumbing, mutable defaults, and large unsharded in-graph
  constants (rules TPU1xx).
- :mod:`.flag_audit` — no silently-ignored config flags (rule FLAG301).
- :mod:`.kernel_audit` — kernel contracts over the :mod:`.kernel_registry`
  enumeration of every ``pallas_call`` in ``ops/``: static VMEM budget,
  Mosaic tile legality, fallback/parity/lowering census, the committed
  tuning table (``tuning_table.json``) kernels read tile defaults through,
  and the MXU-occupancy floor (rules KERN70x), with ``legal_tiles()`` as
  the pruned autotuner search space.

This module stays import-light (no jax) so the retrace-guard hooks can be
wired into the runtime without pulling the analyzers in.
"""

from neuronx_distributed_inference_tpu.analysis.findings import (  # noqa: F401
    Baseline,
    Finding,
    SEV_ERROR,
    SEV_WARNING,
    render_report,
)
from neuronx_distributed_inference_tpu.analysis.retrace_guard import (  # noqa: F401
    RetraceError,
    RetraceGuard,
    guard_enabled,
    note_trace,
    trace_marker,
)
