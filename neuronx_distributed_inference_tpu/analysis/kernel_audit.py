"""Kernel-contract audit (KERN701-705): static VMEM/tile-legality model over
every Pallas kernel in ``ops/``, instantiated at the committed bench shapes
through :mod:`analysis.kernel_registry`.

The repo's kernels ship tiles hand-picked with no TPU in the container. This
suite is the contract layer the ROADMAP autotuner needs: it proves — as
arithmetic, on a CPU-only host — that every committed (kernel, shape, dtype)
instantiation fits the device's scoped VMEM, is Mosaic-tile-legal, names a
native fallback plus parity coverage, and reads its tile defaults from the
committed ``tuning_table.json``; and it enumerates the LEGAL candidate space
(:func:`legal_tiles`) so hardware session zero measures only tiles that can
compile and fit.

Rules
-----
- **KERN701** static VMEM budget: 2x (double-buffered) operand/output block
  windows + ``pltpu.VMEM`` scratch vs ``DeviceSpec.vmem_bytes`` for the
  bench device. Over-budget at any committed shape is an error that cannot
  be baselined away; the per-instance census (vmem bytes, grid, flops/step)
  is pinned in ``kernel_baseline.json`` like the cost census.
- **KERN702** Mosaic tile legality: block last dim a 128-lane multiple (or
  equal to the array dim), sublane multiples by dtype width (8/f32,
  16/bf16, 32/int8-fp8), block-vs-array divisibility per axis, plus the
  prose packing contracts of PRs 6/12 as arithmetic (ragged q-tile divides
  RAGGED_Q_TILE so a tile never spans rows; the speculation segment fits
  one tile).
- **KERN703** kernel census: every ``pl.pallas_call`` site under ``ops/``
  must be claimed by a registry entry; every entry must name an importable
  native fallback, a parity test and a TPU-lowering test that mention its
  entry point.
- **KERN704** tuning table: every registered (kernel, shape-class, dtype)
  with free tile params needs a committed ``tuning_table.json`` entry with
  valid provenance; while provenance is ``hand_picked`` the entry must
  equal the in-code fallback constants (drift check, both directions).
- **KERN705** arithmetic-intensity floor: FLOPs-weighted MXU occupancy of
  the kernel body's dots (contraction depth x output lanes vs the 128x128
  array) and dead (extent-1) grid axes, reconciled against the committed
  census — known sub-floor kernels (the D=64 half-depth family the packed
  kernel exists for) are pinned; a NEW sub-floor kernel or dead axis errors.

Workflow parity with the other suites: ``run(write_baseline=...)``,
``last_report()``, ``render_breakdown()``; regenerate baselines with
``python -m neuronx_distributed_inference_tpu.analysis --suites kernel
--write-baseline`` and review the diff like code.
"""

from __future__ import annotations

import importlib
import itertools
import json
import pathlib
from typing import Dict, List, Optional, Tuple

from neuronx_distributed_inference_tpu.analysis.findings import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
)

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "kernel_baseline.json"
TABLE_PATH = pathlib.Path(__file__).resolve().parent / "tuning_table.json"
REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent

#: FLOPs-weighted MXU-occupancy floor (KERN705). 128x128 MXU: a D=64
#: attention contraction half-fills the array (0.5) — known and pinned; the
#: floor catches kernels that fall BELOW the committed family (e.g. a
#: lane-starved dot at <32 output lanes).
MXU_FLOOR = 0.6

#: sublane multiple per operand byte-width (Mosaic packing): fp32 tiles are
#: (8, 128), bf16 (16, 128), int8/fp8 (32, 128)
SUBLANE_BY_ITEMSIZE = {4: 8, 2: 16, 1: 32}

_LAST_REPORT: Optional[dict] = None


# ---------------------------------------------------------------------------
# baseline + tuning-table IO
# ---------------------------------------------------------------------------


def load_kernel_baseline(path: Optional[pathlib.Path] = None) -> dict:
    p = path or BASELINE_PATH
    if not p.exists():
        return {}
    with open(p) as f:
        return json.load(f)


def save_kernel_baseline(data: dict, path: Optional[pathlib.Path] = None) -> None:
    p = path or BASELINE_PATH
    with open(p, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def load_tuning_table(path: Optional[pathlib.Path] = None) -> dict:
    p = path or TABLE_PATH
    if not p.exists():
        return {}
    with open(p) as f:
        return json.load(f)


def save_tuning_table(data: dict, path: Optional[pathlib.Path] = None) -> None:
    p = path or TABLE_PATH
    with open(p, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# pure comparators (unit-testable both directions without tracing)
# ---------------------------------------------------------------------------


def _occupancy(dot_stats) -> Optional[float]:
    tot = sum(f for f, _, _ in dot_stats)
    if not tot:
        return None
    w = sum(
        f * (min(k, 128) / 128.0) * (min(n, 128) / 128.0) for f, k, n in dot_stats
    )
    return w / tot


def vmem_findings(key: str, location: str, vmem_bytes: int, budget: int) -> List[Finding]:
    """KERN701 hard budget: over-budget is an error, never baselinable."""
    if vmem_bytes <= budget:
        return []
    return [
        Finding(
            rule="KERN701",
            severity=SEV_ERROR,
            location=location,
            message=(
                f"{key}: static VMEM model {vmem_bytes / 2**20:.2f} MiB exceeds "
                f"the {budget / 2**20:.0f} MiB per-core budget "
                f"(double-buffered block windows + scratch) — shrink the tile "
                f"or split the kernel"
            ),
            key=key,
        )
    ]


def census_findings(census: Dict[str, dict], baseline: dict) -> List[Finding]:
    """KERN701 census pin: the committed per-instance numbers must match the
    tree exactly (the model is arithmetic — any drift is a real change)."""
    out = []
    base = baseline.get("census", {})
    for key, row in sorted(census.items()):
        b = base.get(key)
        if b is None:
            out.append(
                Finding(
                    rule="KERN701",
                    severity=SEV_ERROR,
                    location=row["location"],
                    message=(
                        f"{key}: no committed kernel census — run "
                        f"--write-baseline and review/commit kernel_baseline.json"
                    ),
                    key=key,
                )
            )
            continue
        for fieldname in ("vmem_bytes", "grid", "flops_per_step"):
            if b.get(fieldname) != row[fieldname]:
                out.append(
                    Finding(
                        rule="KERN701",
                        severity=SEV_ERROR,
                        location=row["location"],
                        message=(
                            f"{key}: kernel census drift — {fieldname} "
                            f"{b.get(fieldname)} (committed) != {row[fieldname]} "
                            f"(tree); review and --write-baseline if intended"
                        ),
                        key=f"{key}/{fieldname}",
                    )
                )
    for key in sorted(set(base) - set(census)):
        out.append(
            Finding(
                rule="KERN701",
                severity=SEV_WARNING,
                location="analysis/kernel_baseline.json",
                message=(
                    f"{key}: stale kernel census entry (no such registered "
                    f"instance) — --write-baseline to drop it"
                ),
                key=f"stale/{key}",
            )
        )
    return out


def block_legality_findings(
    key: str,
    location: str,
    blocks,
    *,
    dtype_label: str = "",
) -> List[Finding]:
    """KERN702 per-block Mosaic legality. ``blocks`` is an iterable of
    objects with block_shape/array_shape/itemsize (BlockInfo or any stub)."""
    out = []
    for i, b in enumerate(blocks):
        bl, arr = tuple(b.block_shape), tuple(b.array_shape)
        sub = SUBLANE_BY_ITEMSIZE.get(b.itemsize, 8)
        probs = []
        if bl:
            lane_ok = bl[-1] % 128 == 0 or bl[-1] == arr[-1]
            if not lane_ok:
                probs.append(
                    f"last dim {bl[-1]} is neither a 128-lane multiple nor "
                    f"the array dim {arr[-1]}"
                )
        if len(bl) >= 2:
            sub_ok = bl[-2] % sub == 0 or bl[-2] == arr[-2]
            if not sub_ok:
                probs.append(
                    f"sublane dim {bl[-2]} is neither a multiple of {sub} "
                    f"(itemsize {b.itemsize}) nor the array dim {arr[-2]}"
                )
        for ax, (bd, ad) in enumerate(zip(bl, arr)):
            if bd and ad % bd:
                probs.append(
                    f"axis {ax}: array dim {ad} not divisible by block dim "
                    f"{bd} (padded grid would read junk)"
                )
        for p in probs:
            out.append(
                Finding(
                    rule="KERN702",
                    severity=SEV_ERROR,
                    location=location,
                    message=(
                        f"{key}: operand {i} block {bl} over array {arr}: {p}"
                    ),
                    key=f"{key}/block{i}",
                )
            )
    return out


def packing_contract_findings(
    key: str, location: str, tq: int, ragged_q_tile: int, spec_width: int
) -> List[Finding]:
    """KERN702 packing contracts (PR 6/12 prose, as arithmetic): row starts
    are RAGGED_Q_TILE-aligned, so a q tile never spans rows iff tq divides
    RAGGED_Q_TILE; the speculation segment must fit one tile."""
    out = []
    if tq > ragged_q_tile or ragged_q_tile % tq:
        out.append(
            Finding(
                rule="KERN702",
                severity=SEV_ERROR,
                location=location,
                message=(
                    f"{key}: q tile {tq} does not divide RAGGED_Q_TILE "
                    f"{ragged_q_tile} — a tile could span two packed rows"
                ),
                key=f"{key}/rowspan",
            )
        )
    if spec_width > tq:
        out.append(
            Finding(
                rule="KERN702",
                severity=SEV_ERROR,
                location=location,
                message=(
                    f"{key}: speculation segment width {spec_width} exceeds "
                    f"the q tile {tq} — a spec segment must fit one tile"
                ),
                key=f"{key}/specfit",
            )
        )
    return out


def registry_findings(
    sites: List[Tuple[str, str, int]],
    claimed: Dict[Tuple[str, str], str],
    checks: List[dict],
) -> List[Finding]:
    """KERN703: unclaimed pallas_call sites, stale registry sites, fallback/
    test reference failures. ``checks`` rows: {kernel, fallback_ok, fallback,
    parity_ok, parity_test, lowering_ok, lowering_test, entry}."""
    out = []
    site_set = {(f, fn) for f, fn, _ in sites}
    for f, fn, line in sorted(sites):
        if (f, fn) not in claimed:
            out.append(
                Finding(
                    rule="KERN703",
                    severity=SEV_ERROR,
                    location=f"ops/{f}:{line}",
                    message=(
                        f"unregistered pallas_call in {fn}(): every kernel "
                        f"must be enumerated in analysis/kernel_registry.py "
                        f"with a fallback, parity test and lowering test"
                    ),
                    key=f"unregistered/{f}/{fn}",
                )
            )
    for (f, fn), kernel in sorted(claimed.items()):
        if (f, fn) not in site_set:
            out.append(
                Finding(
                    rule="KERN703",
                    severity=SEV_ERROR,
                    location=f"ops/{f}",
                    message=(
                        f"{kernel}: registry claims a pallas_call in {fn}() "
                        f"but none exists — stale registry entry"
                    ),
                    key=f"stale-site/{f}/{fn}",
                )
            )
    for row in checks:
        k = row["kernel"]
        if not row["fallback_ok"]:
            out.append(
                Finding(
                    rule="KERN703",
                    severity=SEV_ERROR,
                    location="analysis/kernel_registry.py",
                    message=(
                        f"{k}: native fallback {row['fallback']} does not "
                        f"import — every kernel must name a working fallback"
                    ),
                    key=f"fallback/{k}",
                )
            )
        if not row["parity_ok"]:
            out.append(
                Finding(
                    rule="KERN703",
                    severity=SEV_ERROR,
                    location=row["parity_test"],
                    message=(
                        f"{k}: parity test {row['parity_test']} is missing or "
                        f"never references {row['entry']}"
                    ),
                    key=f"parity/{k}",
                )
            )
        if not row["lowering_ok"]:
            out.append(
                Finding(
                    rule="KERN703",
                    severity=SEV_ERROR,
                    location=row["lowering_test"],
                    message=(
                        f"{k}: TPU lowering test {row['lowering_test']} is "
                        f"missing or never references {row['entry']}"
                    ),
                    key=f"lowering/{k}",
                )
            )
    return out


def table_findings(
    required: List[dict],
    table: dict,
) -> List[Finding]:
    """KERN704. ``required`` rows: {kernel (table key), shape_class, dtype,
    tile_params, hand_picked (dict|None), location}. Checks coverage,
    provenance validity, and hand_picked<->in-code drift both directions."""
    out = []
    kernels = table.get("kernels", {})
    seen = set()
    for row in required:
        k, sc, dt = row["kernel"], row["shape_class"], row["dtype"]
        seen.add((k, sc, dt))
        entry = kernels.get(k, {}).get(sc, {}).get(dt)
        keybase = f"{k}/{sc}/{dt}"
        if not isinstance(entry, dict):
            out.append(
                Finding(
                    rule="KERN704",
                    severity=SEV_ERROR,
                    location="analysis/tuning_table.json",
                    message=(
                        f"{keybase}: no tuning-table entry for a registered "
                        f"kernel instantiation — run --write-baseline to seed "
                        f"hand_picked defaults and commit the table"
                    ),
                    key=f"missing/{keybase}",
                )
            )
            continue
        prov = entry.get("provenance")
        if prov not in ("hand_picked", "measured"):
            out.append(
                Finding(
                    rule="KERN704",
                    severity=SEV_ERROR,
                    location="analysis/tuning_table.json",
                    message=(
                        f"{keybase}: invalid provenance {prov!r} (must be "
                        f"hand_picked or measured)"
                    ),
                    key=f"provenance/{keybase}",
                )
            )
        tiles = entry.get("tiles", {})
        missing = [p for p in row["tile_params"] if p not in tiles]
        if missing:
            out.append(
                Finding(
                    rule="KERN704",
                    severity=SEV_ERROR,
                    location="analysis/tuning_table.json",
                    message=(
                        f"{keybase}: table entry missing tile params {missing}"
                    ),
                    key=f"params/{keybase}",
                )
            )
        hand = row.get("hand_picked")
        if prov == "hand_picked" and hand:
            for p, v in hand.items():
                if p in tiles and int(tiles[p]) != int(v):
                    out.append(
                        Finding(
                            rule="KERN704",
                            severity=SEV_ERROR,
                            location="analysis/tuning_table.json",
                            message=(
                                f"{keybase}: hand_picked table value {p}="
                                f"{tiles[p]} drifted from the in-code default "
                                f"{v} — either revert, or regenerate on "
                                f"hardware and promote to measured"
                            ),
                            key=f"drift/{keybase}/{p}",
                        )
                    )
    for k, per_k in sorted(kernels.items()):
        for sc, per_sc in sorted(per_k.items()):
            for dt in sorted(per_sc):
                if (k, sc, dt) not in seen:
                    out.append(
                        Finding(
                            rule="KERN704",
                            severity=SEV_WARNING,
                            location="analysis/tuning_table.json",
                            message=(
                                f"{k}/{sc}/{dt}: tuning-table entry has no "
                                f"registered kernel instantiation — stale?"
                            ),
                            key=f"stale/{k}/{sc}/{dt}",
                        )
                    )
    return out


def mxu_findings(
    census: Dict[str, dict], baseline: dict, floor: float = MXU_FLOOR
) -> List[Finding]:
    """KERN705: sub-floor MXU occupancy / dead grid axes not pinned in the
    committed census. Pinned flags (the known D=64 half-depth family, the
    batch-1 bench grids) stay silent; anything new errors."""
    out = []
    pinned = baseline.get("mxu_flags", {})
    for key, row in sorted(census.items()):
        flags = {}
        occ = row.get("occupancy")
        if occ is not None and occ < floor:
            flags["occupancy"] = occ
        dead = row.get("dead_axes") or []
        if dead:
            flags["dead_axes"] = dead
        if not flags:
            continue
        pin = pinned.get(key)
        if pin is not None and pin.get("occupancy") == flags.get("occupancy") and pin.get("dead_axes", []) == flags.get("dead_axes", []):
            continue
        what = []
        if "occupancy" in flags:
            what.append(
                f"FLOPs-weighted MXU occupancy {flags['occupancy']:.3f} < "
                f"floor {floor} (contraction depth / output lanes under-fill "
                f"the 128x128 array)"
            )
        if "dead_axes" in flags:
            what.append(f"dead (extent-1) grid axes {flags['dead_axes']}")
        out.append(
            Finding(
                rule="KERN705",
                severity=SEV_ERROR,
                location=row["location"],
                message=(
                    f"{key}: {'; '.join(what)} — not pinned in the committed "
                    f"census (cost-audit reconciliation: intensity "
                    f"{row.get('intensity', 0):.1f} FLOP/byte, {row.get('bound')}-"
                    f"bound vs the bench device ridge); --write-baseline if "
                    f"this tile/shape trade-off is intended"
                ),
                key=key,
            )
        )
    return out


# ---------------------------------------------------------------------------
# legal-tile enumeration (KERN704's generator — the autotuner search space)
# ---------------------------------------------------------------------------


def _instance_signature(spec, case, tiles):
    """Trace the candidate; return a hashable (grid, blocks, scratch)
    signature if it passes KERN701/702, else None. The signature also
    collapses clamp-duplicates (two requested tiles that trace the same
    kernel are one candidate)."""
    from neuronx_distributed_inference_tpu.analysis import kernel_registry as kr
    from neuronx_distributed_inference_tpu.analysis.device_model import get_device

    try:
        inst = kr.instantiate(spec, case, tiles=tiles)
    except Exception:
        return None  # the wrapper itself rejects the tiling
    budget = get_device().vmem_bytes
    if vmem_findings(inst.key, "x", inst.vmem_bytes, budget):
        return None
    if block_legality_findings(inst.key, "x", inst.blocks):
        return None
    if spec.name == "ragged_paged_attention":
        from neuronx_distributed_inference_tpu.analysis.programs import _SPEC_WIDTH
        from neuronx_distributed_inference_tpu.ops.ragged_paged_attention import (
            RAGGED_Q_TILE,
        )

        if packing_contract_findings(
            inst.key, "x", tiles.get("tq", RAGGED_Q_TILE), RAGGED_Q_TILE, _SPEC_WIDTH
        ):
            return None
    return (
        tuple(inst.grid),
        tuple(tuple(b.block_shape) for b in inst.blocks),
        inst.scratch_bytes,
    )


def legal_tiles(kernel: str, shape_class: str, dtype: str) -> List[Dict[str, int]]:
    """Enumerate the tile candidates for (kernel, shape-class, dtype) that
    pass KERN701 (VMEM) and KERN702 (legality) at the committed shapes —
    the pruned search space the profile sweeps and (eventually) the
    hardware autotuner measure. Candidates come from the registry's sweep
    axes; each is instantiated through the SAME tile-lookup path a
    committed table entry would use."""
    from neuronx_distributed_inference_tpu.analysis import kernel_registry as kr

    spec = next((s for s in kr.REGISTRY if s.name == kernel), None)
    if spec is None:
        raise KeyError(f"unknown kernel {kernel!r}")
    case = next(
        (
            c
            for c in spec.cases
            if c.shape_class == shape_class and c.dtype == dtype
        ),
        None,
    )
    if case is None:
        raise KeyError(f"{kernel}: no committed case {shape_class}/{dtype}")
    if not spec.sweep:
        return []
    names = [n for n, _ in spec.sweep]
    out = []
    seen_sigs = set()
    for combo in itertools.product(*(vals for _, vals in spec.sweep)):
        tiles = dict(zip(names, combo))
        sig = _instance_signature(spec, case, tiles)
        if sig is None or sig in seen_sigs:
            # illegal, or a clamp-duplicate (e.g. bs > S_kv clamps to S_kv
            # and traces the identical grid/blocks as the clamped value)
            continue
        seen_sigs.add(sig)
        out.append(tiles)
    return out


# ---------------------------------------------------------------------------
# suite entry point
# ---------------------------------------------------------------------------


def _census_row(inst, ridge: float) -> dict:
    occ = _occupancy(inst.dot_stats)
    bytes_step = inst.block_bytes_single
    intensity = inst.flops_per_step / bytes_step if bytes_step else 0.0
    return {
        "location": f"ops/{inst.kernel}",
        "vmem_bytes": inst.vmem_bytes,
        "scratch_bytes": inst.scratch_bytes,
        "grid": list(inst.grid),
        "flops_per_step": inst.flops_per_step,
        "tiles": dict(inst.tiles),
        "occupancy": round(occ, 3) if occ is not None else None,
        "dead_axes": [i for i, g in enumerate(inst.grid) if g == 1],
        "intensity": round(intensity, 2),
        "bound": "compute" if intensity >= ridge else "memory",
    }


def run(
    write_baseline: bool = False,
    baseline_path: Optional[pathlib.Path] = None,
    table_path: Optional[pathlib.Path] = None,
) -> List[Finding]:
    """Run KERN701-705; returns unbaselinable findings (the census/table
    pins already encode the baseline, so everything returned is NEW)."""
    global _LAST_REPORT
    from neuronx_distributed_inference_tpu.analysis import kernel_registry as kr
    from neuronx_distributed_inference_tpu.analysis.device_model import get_device
    from neuronx_distributed_inference_tpu.analysis.programs import _SPEC_WIDTH
    from neuronx_distributed_inference_tpu.ops.ragged_paged_attention import (
        RAGGED_Q_TILE,
    )

    device = get_device()
    budget = device.vmem_bytes
    ridge = device.ridge_flops_per_byte

    findings: List[Finding] = []
    instances = kr.collect_instances()
    census: Dict[str, dict] = {}
    site_of = {s.name: s.site for s in kr.REGISTRY}
    for inst in instances:
        f, fn = site_of[inst.kernel]
        loc = f"ops/{f}:{fn}"
        row = _census_row(inst, ridge)
        row["location"] = loc
        census[inst.key] = row
        findings += vmem_findings(inst.key, loc, inst.vmem_bytes, budget)
        findings += block_legality_findings(inst.key, loc, inst.blocks)
        if inst.kernel == "ragged_paged_attention":
            findings += packing_contract_findings(
                inst.key, loc, inst.tiles.get("tq", RAGGED_Q_TILE),
                RAGGED_Q_TILE, _SPEC_WIDTH,
            )

    # KERN703 census
    sites = kr.pallas_sites()
    claimed = {s.site: s.name for s in kr.REGISTRY}
    checks = []
    for s in kr.REGISTRY:
        mod, _, attr = s.fallback.partition(":")
        try:
            fallback_ok = hasattr(importlib.import_module(mod), attr)
        except ImportError:
            fallback_ok = False

        def _mentions(rel: str, needle: str) -> bool:
            p = REPO_ROOT / rel
            return p.exists() and needle in p.read_text()

        checks.append(
            {
                "kernel": s.name,
                "entry": s.entry,
                "fallback": s.fallback,
                "fallback_ok": fallback_ok,
                "parity_test": s.parity_test,
                "parity_ok": _mentions(s.parity_test, s.entry),
                "lowering_test": s.lowering_test,
                "lowering_ok": _mentions(s.lowering_test, s.entry),
            }
        )
    findings += registry_findings(sites, claimed, checks)

    # KERN704 tuning table
    table = load_tuning_table(table_path)
    required = []
    for s in kr.REGISTRY:
        if not s.tile_params:
            continue
        for c in s.cases:
            required.append(
                {
                    "kernel": s.table_key,
                    "shape_class": c.shape_class,
                    "dtype": c.dtype,
                    "tile_params": s.tile_params,
                    "hand_picked": kr.hand_picked_tiles(s.table_key, c.shape_class),
                    "location": f"ops/{s.site[0]}",
                }
            )
    if write_baseline:
        kernels = table.setdefault("kernels", {})
        table.setdefault(
            "comment",
            "Tile defaults per (kernel, shape-class, dtype). provenance "
            "hand_picked mirrors the in-code constants (KERN704 pins them "
            "equal); hardware sweeps promote entries to measured.",
        )
        for row in required:
            per = kernels.setdefault(row["kernel"], {}).setdefault(
                row["shape_class"], {}
            )
            if row["dtype"] not in per:
                per[row["dtype"]] = {
                    "tiles": dict(row["hand_picked"] or {}),
                    "provenance": "hand_picked",
                }
        save_tuning_table(table, table_path)
        from neuronx_distributed_inference_tpu.ops import tile_defaults

        tile_defaults.reload_table()
        table = load_tuning_table(table_path)
    findings += table_findings(required, table)

    # KERN701 census pin + KERN705 occupancy flags
    baseline = load_kernel_baseline(baseline_path)
    if write_baseline:
        mxu_flags = {}
        for key, row in census.items():
            flags = {}
            if row["occupancy"] is not None and row["occupancy"] < MXU_FLOOR:
                flags["occupancy"] = row["occupancy"]
            if row["dead_axes"]:
                flags["dead_axes"] = row["dead_axes"]
            if flags:
                mxu_flags[key] = flags
        baseline = {
            "census": {
                k: {
                    f: v
                    for f, v in row.items()
                    if f in ("vmem_bytes", "grid", "flops_per_step", "tiles",
                             "occupancy", "intensity", "bound", "scratch_bytes")
                }
                for k, row in sorted(census.items())
            },
            "mxu_flags": mxu_flags,
        }
        save_kernel_baseline(baseline, baseline_path)
    findings += census_findings(census, baseline)
    findings += mxu_findings(census, baseline)

    _LAST_REPORT = {
        "device": device.name,
        "vmem_budget": budget,
        "instances": census,
        "n_sites": len(sites),
        "n_registered": len(kr.REGISTRY),
        "findings": len(findings),
    }
    return findings


def last_report() -> Optional[dict]:
    return _LAST_REPORT


def render_breakdown(report: Optional[dict]) -> str:
    if not report:
        return ""
    lines = [
        f"kernel audit: {report['n_registered']} registered kernels over "
        f"{report['n_sites']} pallas_call sites, device {report['device']} "
        f"(VMEM budget {report['vmem_budget'] / 2**20:.0f} MiB)",
        f"{'instance':46s} {'grid':>16s} {'vmem':>9s} {'occ':>5s} "
        f"{'AI':>8s} bound",
    ]
    for key, row in sorted(report["instances"].items()):
        occ = row["occupancy"]
        lines.append(
            f"{key:46s} {str(tuple(row['grid'])):>16s} "
            f"{row['vmem_bytes'] / 2**20:8.2f}M "
            f"{occ if occ is not None else 0:5.2f} "
            f"{row['intensity']:8.1f} {row['bound']}"
        )
    return "\n".join(lines)
