"""Shared finding / baseline machinery for the static-analysis passes.

Every analysis pass (tpulint AST rules, the config flag audit, the jaxpr/HLO
graph audit) emits :class:`Finding` records — rule id, severity, location
(``file:line`` for source rules, ``tag/bucket`` for graph rules), message —
so one CLI renders them as text or JSON and ONE baseline mechanism decides
what is allowed to exist.

Baseline model: a committed JSON file maps ``rule -> location-key -> count``.
A finding is *baselined* (allowed) while its (rule, key) bucket still has
budget; anything beyond the recorded count is NEW and fails the run. Counts —
not line numbers — are pinned so unrelated edits don't churn the baseline,
while a new ``jax.device_get`` in a file immediately trips the gate (the
"pins the count" contract of the host-sync rule).

In-code escape hatch: a ``# tpulint: ignore[RULE]`` comment on the offending
line (or its enclosing ``def`` line) suppresses a source finding with a
written-down justification right at the site.
"""

from __future__ import annotations

import dataclasses
import json
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"

#: container-mutating method names — a call through one of these IS a write
#: to the receiver. Shared by tpulint's TPU109 (module-level mutable state
#: in runtime/) and the concurrency audit's CONC601 write-site census, so
#: the two rules can never disagree about what counts as a write.
CONTAINER_MUTATORS = frozenset({
    "append", "extend", "appendleft", "popleft", "pop", "clear", "update",
    "add", "remove", "discard", "insert", "setdefault", "sort",
})


@dataclass(frozen=True)
class Finding:
    """One analysis finding.

    ``location`` is ``path/to/file.py:LINE`` for source rules and
    ``tag/bucket`` (e.g. ``token_generation/128``) for graph rules.
    ``key`` is the baseline bucket the finding counts against — file path for
    source rules, tag for graph rules — deliberately coarser than
    ``location`` so baselines survive unrelated line churn.
    """

    rule: str
    severity: str
    location: str
    message: str
    key: str = ""

    def baseline_key(self) -> Tuple[str, str]:
        return (self.rule, self.key or self.location)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.location}: {self.severity} [{self.rule}] {self.message}"


@dataclass
class Baseline:
    """Committed allowance: ``rule -> key -> count``."""

    counts: Dict[str, Dict[str, int]] = field(default_factory=dict)

    @classmethod
    def load(cls, path) -> "Baseline":
        try:
            with open(path) as f:
                data = json.load(f)
        except FileNotFoundError:
            return cls()
        return cls(counts={r: dict(v) for r, v in data.get("counts", {}).items()})

    def save(self, path):
        with open(path, "w") as f:
            json.dump(
                {"counts": {r: dict(sorted(v.items())) for r, v in sorted(self.counts.items())}},
                f, indent=2, sort_keys=True,
            )
            f.write("\n")

    @classmethod
    def from_findings(cls, findings: List[Finding]) -> "Baseline":
        c = Counter(f.baseline_key() for f in findings)
        counts: Dict[str, Dict[str, int]] = {}
        for (rule, key), n in c.items():
            counts.setdefault(rule, {})[key] = n
        return cls(counts=counts)

    def filter_new(self, findings: List[Finding]) -> List[Finding]:
        """Findings beyond the recorded per-(rule, key) budget, i.e. the ones
        that must fail the run. Within a bucket the EXCESS findings are
        reported (ordering inside a bucket is by location, so reports are
        stable)."""
        budget = Counter()
        for rule, keys in self.counts.items():
            for key, n in keys.items():
                budget[(rule, key)] = n
        new: List[Finding] = []
        for f in sorted(findings, key=lambda f: (f.rule, f.key, f.location)):
            k = f.baseline_key()
            if budget[k] > 0:
                budget[k] -= 1
            else:
                new.append(f)
        return new


def render_report(
    findings: List[Finding],
    new_findings: List[Finding],
    as_json: bool = False,
    suites: Optional[List[str]] = None,
    extras: Optional[Dict] = None,
    extras_text: Optional[str] = None,
) -> str:
    """Text or JSON report. JSON carries every finding plus the subset that
    is new (non-baselined); text shows new findings and a summary line.

    ``extras`` merges suite-specific payloads into the JSON report (e.g. the
    memory audit's per-bucket HBM breakdown under ``"memory"``);
    ``extras_text`` is its pre-rendered text-mode counterpart."""
    if as_json:
        payload = {
            "suites": suites or [],
            "total": len(findings),
            "new": len(new_findings),
            "findings": [f.to_dict() for f in findings],
            "new_findings": [f.to_dict() for f in new_findings],
        }
        if extras:
            payload.update(extras)
        return json.dumps(payload, indent=2)
    lines = []
    for f in new_findings:
        lines.append(f.render())
    if extras_text:
        lines.append(extras_text)
    lines.append(
        f"{len(findings)} finding(s), {len(new_findings)} new (non-baselined)"
        + (f" [suites: {', '.join(suites)}]" if suites else "")
    )
    return "\n".join(lines)
