"""tpulint: AST rules specific to this codebase.

The rules encode contracts the runtime relies on but Python cannot enforce:

- **TPU101 host-sync-under-trace** (error): ``jax.device_get``,
  ``block_until_ready`` or ``.item()`` inside a jit-traced function body. At
  trace time these force the tracer to a concrete value (ConcretizationError
  at best, a silent constant-fold at worst); they belong in host loops only.
- **TPU102 host-sync-census** (warning, baselined): EVERY host-sync call in
  the package, counted per file. The committed baseline pins the count — the
  batched ``jax.device_get((tokens, logits))`` work in runtime/ stays pinned
  so a new per-field fetch in a hot loop fails the lint. Calls inside the
  hot-path function sets of :data:`HOT_PATH_BUCKETS` additionally count
  against separately-pinned per-file buckets — the serving ``step()`` hot
  path (:data:`SERVING_STEP_HOT_PATH`, ``::step-hot-path``) and the
  router's placement/failover loop (:data:`ROUTER_HOT_PATH`,
  ``::route-hot-path``, pinned at ZERO) — so a blocking fetch added to a
  per-step loop trips the gate on its own: the pipelined ragged dispatch
  depends on the step path staying fetch-free outside the designated
  consume points, and the multi-replica router would serialize every
  replica behind one device.
- **TPU103 host-time-under-trace** (error): ``time.time()`` /
  ``time.perf_counter()`` / ``print`` under trace — they execute ONCE at
  trace time and then lie forever.
- **TPU104 pallas-missing-interpret** (error): a ``pallas_call`` site
  without the ``interpret=`` kwarg, i.e. a kernel outside the
  ``ops/kernel_mode.py`` plumbing. Such a kernel cannot run on the CPU test
  mesh and cannot be forced to compile for the AOT Mosaic-lowering tests
  (the r1/r3 bench-only crash class).
- **TPU105 mutable-default-arg** (error): a list/dict/set literal default
  argument anywhere in the package.
- **TPU106 np-under-trace** (warning, baselined): ``np.asarray``/``np.array``
  inside a traced body. Legitimate on trace-time-static values (bucket
  tables, permutations) — those sites carry a pragma or a baseline entry —
  but on a traced value it synchronizes or crashes.
- **TPU107 metric-recording-under-trace** (error): a telemetry call inside a
  jit-traced body — any reference to a symbol imported from the
  ``telemetry`` package, or a ``.inc(...)``/``.observe(...)`` metric-method
  call. Python under trace runs ONCE per compile, so a metric recorded
  there counts compiles, not steps — it would lie forever (TPU103's
  failure mode) AND any telemetry that *read* a traced value would force a
  host sync (TPU101's). Recording belongs in host loops, on values the
  step's existing batched fetch already landed; this rule is the static
  half of the zero-device-round-trip telemetry contract
  (docs/OBSERVABILITY.md).
- **TPU109 module-level-mutable-state** (warning, baselined — zero entries
  expected): a dict/list/set (literal or ``dict()``/``list()``/``set()``/
  ``deque()``/``defaultdict()`` call) assigned at module level in
  ``runtime/`` that any function then WRITES (subscript assignment, a
  mutating method call, or a ``global`` rebind). Import-time mutable state
  written from functions is the classic hidden-shared-state smell the
  concurrency audit's census rules (CONC601) key off: it has no owning
  object, so no confinement argument covers it — under thread-per-replica
  stepping it is a cross-replica race waiting to happen. Put the state on
  an owning class (where the CONC601 ownership model classifies it) or
  suppress with a written-down justification (e.g. a decoration-time-only
  registry).
- **TPU110 silent-swallow** (warning, baselined — zero entries expected):
  a bare ``except:`` or ``except Exception/BaseException:`` handler whose
  body is only ``pass`` in ``runtime/`` or ``telemetry/``. A swallowed
  failure on a serving or observability path is an invisible leak — the
  containment story (typed degradation, loud failure) depends on every
  broad catch either handling or re-raising. Catch the typed class (see the
  narrowed ``compilation_cache`` guard in runtime/application.py) or let it
  propagate. The lifecycle audit (LIFE803) carries the ERROR-level version
  for runtime/.
- **TPU108 large-unsharded-constant** (warning, baselined — zero entries
  expected): a ``jnp.zeros/ones/full/arange/eye/...`` call with a
  STATICALLY-known element count ≥ 2**20 inside a jit-traced body, not
  wrapped in a sharding constraint (``with_sharding_constraint`` /
  ``constrain`` / ``device_put``). GSPMD replicates unconstrained
  constants, so a large table materialized in-graph silently costs
  model-group× its HBM — this catches it at the AST, before the shard
  audit (GRAPH301/302) ever sees a compile. Census format shared with
  TPU102 (per-file counts against the committed baseline).

Traced-body detection: a function is *traced* when it is (a) decorated with
``jax.jit`` (possibly through ``partial``), (b) referenced anywhere inside a
``jax.jit(...)`` call's arguments (covers ``jax.jit(partial(forward, ...))``
and the retrace-guard ``trace_marker`` wrappers, resolved across modules
through the import graph), (c) defined inside a traced function, or (d)
reachable from a traced function through package-internal calls/references
(fixpoint propagation — ``forward -> model_logits -> decoder_layer`` all
count). This overapproximates (a function used both host-side and in-graph
counts as traced), which is the correct direction for a contract check.

Suppression: ``# tpulint: ignore[TPU101]`` (or a bare ``# tpulint: ignore``)
on the offending line or its enclosing ``def`` line.
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from neuronx_distributed_inference_tpu.analysis.findings import (
    CONTAINER_MUTATORS,
    Finding,
    SEV_ERROR,
    SEV_WARNING,
)

PACKAGE = "neuronx_distributed_inference_tpu"

HOST_SYNC_ATTRS = {"device_get", "block_until_ready", "item"}
HOST_TIME_FUNCS = {"time", "perf_counter", "monotonic"}
NP_SYNC_FUNCS = {"asarray", "array"}
# telemetry recording: the package prefix (import-based detection) and the
# metric mutator names distinctive enough to flag bare (heuristic half —
# catches `self.tel.inc/observe`-style calls the import map cannot resolve)
TELEMETRY_PKG = PACKAGE + "/telemetry"
METRIC_RECORD_ATTRS = {"inc", "observe"}

# TPU108: jnp array creators whose result REPLICATES when unconstrained
# under GSPMD (the *_like variants inherit their prototype's sharding and
# are exempt), and the element-count threshold above which a replicated
# constant is an HBM problem worth flagging (2**20 elems = 4 MiB in f32,
# PER DEVICE, times the model-group size).
JNP_ARRAY_CREATORS = {"zeros", "ones", "full", "empty", "arange", "eye", "linspace"}
TPU108_ELEM_THRESHOLD = 1 << 20
# wrappers that give the fresh array a placement, silencing TPU108
SHARDING_WRAPPERS = {"with_sharding_constraint", "constrain", "device_put"}

# TPU109: constructors whose module-level result is mutable shared state
# (the write-counting mutator set is findings.CONTAINER_MUTATORS, shared
# with the concurrency audit's CONC601 census), and the package subtree the
# rule audits (the serving runtime — where the thread-per-replica router
# makes hidden module state an actual race)
MUTABLE_CONSTRUCTORS = {"dict", "list", "set", "deque", "defaultdict",
                        "OrderedDict", "Counter"}
TPU109_SCOPE_PREFIX = PACKAGE + "/runtime/"

_PRAGMA_RE = re.compile(r"#\s*tpulint:\s*ignore(?:\[([A-Z0-9, ]+)\])?")

#: ServingSession step() hot-path functions (runtime/serving.py): every
#: method a scheduler tick runs through. Host-sync calls inside them get a
#: SECOND TPU102 census finding keyed `<file>::step-hot-path`, pinned
#: separately by the baseline, so a future blocking `jax.device_get` added
#: to the per-step loop (outside the designated consume points) fails the
#: gate even when the file-level count is rebalanced. The speculative
#: session's accept/reject fetch in `_step_inner` is the one designated
#: (baselined) entry.
SERVING_STEP_HOT_PATH = {
    "step",
    "_step_inner",
    "_ragged_step",
    "_schedule_mixed",
    "_build_mixed_descriptors",
    "_consume_ragged",
    # spec-ragged speculation path (serving_spec_ragged): the packed verify
    # consume rides ONE np.asarray (deliberately not a census name — the
    # async fetch was started at dispatch) and the chained draft must stay
    # fetch-free (its whole point is the frontier never visiting the host)
    "_spec_ragged_step",
    "_schedule_spec",
    "_consume_spec",
    "_dispatch_chained_draft",
    "_note_acceptance",
    "_dispatch_decode",
    "_consume",
    "_prefill_chunks",
    "_decode_drain",
    "_decode_chunk_pass",
}

#: ServingRouter per-tick functions (runtime/router.py): the placement /
#: health / failover loop over N replicas. Pure host bookkeeping by
#: contract — a blocking device fetch here would serialize EVERY replica
#: behind one device, so its census bucket
#: (`runtime/router.py::route-hot-path`) is pinned at ZERO entries.
ROUTER_HOT_PATH = {
    "step",
    "_place_pending",
    "_candidates",
    "_sync_terminals",
    "_failover_request",
    "_failover_replica",
    "_publish_gauges",
    "run_to_completion",
    # thread-per-replica stepping (router_threading): the stepping phase +
    # the worker protocol — router.py-side code here must stay fetch-free
    # (the per-replica session's designated consume points live in
    # serving.py's own bucket; a fetch in the worker loop or the barrier
    # would re-serialize every replica behind one device)
    "_step_replicas",
    "run",
    "dispatch",
    "wait_done",
    "join_step",
}

#: WorkloadDriver per-tick functions (workload/driver.py): the open-loop
#: admission / chaos / commit-attribution loop wrapped around every router
#: (or session) step. Pure host bookkeeping by contract — commit counts
#: are read from host-side request records, never fetched — so its census
#: bucket (`workload/driver.py::drive-hot-path`) is pinned at ZERO entries.
DRIVER_HOT_PATH = {
    "step",
    "run",
    "_admit_due",
    "_maybe_kill",
    "_record_step",
    "_committed_of",
    "_has_live_work",
    "_backlog_depth",
}

#: ServingRouter disaggregated hand-off functions (runtime/router.py): the
#: prefill-tier placement path. The ONE designated hand-off sync (the
#: payload finiteness reduce) lives in runtime/disaggregated.py's
#: validate_handoff_payload — router.py-side hand-off code is pure host
#: bookkeeping, so its census bucket
#: (`runtime/router.py::handoff-hot-path`) is pinned at ZERO entries.
ROUTER_HANDOFF_HOT_PATH = {
    "_bind_replica",
    "_handoff",
    "_local_prefill",
    "_pick_prefill",
    "_publish_tier_gauges",
}

#: per-file hot-path census buckets: {relpath suffix: tuple of (bucket
#: label, function-name set, human description of why a fetch there is a
#: bug)} — a file may pin SEVERAL independent buckets (router.py pins the
#: placement loop and the hand-off path separately)
HOT_PATH_BUCKETS = {
    "runtime/serving.py": (
        (
            "step-hot-path",
            SERVING_STEP_HOT_PATH,
            "a blocking fetch here stalls the pipelined serving loop; "
            "consume points only",
        ),
    ),
    "runtime/router.py": (
        (
            "route-hot-path",
            ROUTER_HOT_PATH,
            "a blocking fetch in the placement loop serializes every replica "
            "behind one device; the router is host bookkeeping only",
        ),
        (
            "handoff-hot-path",
            ROUTER_HANDOFF_HOT_PATH,
            "a blocking fetch in the hand-off path would stall every "
            "placement behind one transfer; the designated hand-off sync "
            "lives in disaggregated.validate_handoff_payload",
        ),
    ),
    "workload/driver.py": (
        (
            "drive-hot-path",
            DRIVER_HOT_PATH,
            "a blocking fetch in the open-loop driver would bill device "
            "waits as workload time; the driver reads host-side commit "
            "records only",
        ),
    ),
}


@dataclass
class _FuncInfo:
    module: str  # module path relative to repo root
    name: str  # bare name
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    refs: Set[Tuple[str, str]] = field(default_factory=set)  # resolved (module, name)
    traced: bool = False


class _ModuleIndex:
    """Per-module: source, pragma lines, import map, function table."""

    def __init__(self, path: pathlib.Path, relpath: str, root: pathlib.Path):
        self.path = path
        self.relpath = relpath
        self.source = path.read_text()
        self.tree = ast.parse(self.source, filename=str(path))
        self.pragmas = self._collect_pragmas()
        # local name -> fully-resolved in-package module relpath (aliases for
        # `import pkg.x as y` and symbols for `from pkg.x import f`)
        self.import_modules: Dict[str, str] = {}
        self.import_symbols: Dict[str, Tuple[str, str]] = {}
        self._collect_imports(root)
        self.functions: Dict[str, List[_FuncInfo]] = {}
        # simple name -> assigned RHS expressions, so the two-step pattern
        # `step = partial(forward, ...); jax.jit(step)` still seeds `forward`
        self.assignments: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.assignments.setdefault(t.id, []).append(node.value)

    def _collect_pragmas(self) -> Dict[int, Set[str]]:
        pragmas: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.source.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                rules = m.group(1)
                pragmas[i] = (
                    {r.strip() for r in rules.split(",")} if rules else {"*"}
                )
        return pragmas

    def _mod_to_relpath(self, dotted: str, root: pathlib.Path) -> Optional[str]:
        if not dotted.startswith(PACKAGE):
            return None
        p = root / (dotted.replace(".", "/") + ".py")
        if p.is_file():
            return str(p.relative_to(root))
        p = root / dotted.replace(".", "/") / "__init__.py"
        if p.is_file():
            return str(p.relative_to(root))
        return None

    def _collect_imports(self, root: pathlib.Path):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    rp = self._mod_to_relpath(a.name, root)
                    if rp:
                        self.import_modules[a.asname or a.name.split(".")[-1]] = rp
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod = self._mod_to_relpath(node.module, root)
                for a in node.names:
                    if mod:
                        sub = self._mod_to_relpath(f"{node.module}.{a.name}", root)
                        if sub:
                            # `from pkg.x import submodule`
                            self.import_modules[a.asname or a.name] = sub
                        else:
                            self.import_symbols[a.asname or a.name] = (mod, a.name)

    def suppressed(self, line: int, rule: str, def_line: Optional[int] = None) -> bool:
        for ln in (line, def_line):
            if ln is None:
                continue
            rules = self.pragmas.get(ln)
            if rules and ("*" in rules or rule in rules):
                return True
        return False


def _names_in(expr: ast.AST) -> List[ast.AST]:
    """Every Name / module-attribute reference inside an expression tree."""
    out = []
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            out.append(n)
        elif isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name):
            out.append(n)
    return out


def _static_elem_count(call: ast.Call) -> Optional[int]:
    """Element count of a jnp array-creating call when it is statically
    decidable from literal arguments (positional OR keyword — a
    ``jnp.zeros(shape=(4096, 4096))`` is just as provably large); None when
    shape flows from variables (the conservative direction for a lint: only
    flag what is PROVABLY large)."""
    name = call.func.attr if isinstance(call.func, ast.Attribute) else None
    kwargs = {k.arg: k.value for k in call.keywords if k.arg}

    def arg(pos: int, kw: str):
        if pos < len(call.args):
            return call.args[pos]
        return kwargs.get(kw)

    def _lit_int(node) -> Optional[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return node.value
        return None

    def _shape_count(node) -> Optional[int]:
        if node is None:
            return None
        one = _lit_int(node)
        if one is not None:
            return one
        if isinstance(node, (ast.Tuple, ast.List)):
            total = 1
            for el in node.elts:
                d = _lit_int(el)
                if d is None:
                    return None
                total *= d
            return total
        return None

    if name in ("zeros", "ones", "full", "empty"):
        return _shape_count(arg(0, "shape"))
    if name == "arange":
        # arange(stop) / arange(start, stop[, step]) with literal ints
        nodes = [arg(0, "start"), arg(1, "stop"), arg(2, "step")]
        vals = [None if n is None else _lit_int(n) for n in nodes]
        if nodes[0] is None or vals[0] is None:
            return None
        if nodes[1] is None:
            return max(0, vals[0])  # arange(stop)
        if vals[1] is None:
            return None
        step = 1 if nodes[2] is None else vals[2]
        if not step:
            return None
        return max(0, -(-(vals[1] - vals[0]) // step))
    if name == "eye":
        n = _lit_int(arg(0, "N")) if arg(0, "N") is not None else None
        m_node = arg(1, "M")
        m = _lit_int(m_node) if m_node is not None else n
        return None if n is None or m is None else n * m
    if name == "linspace":
        num_node = arg(2, "num")
        return 50 if num_node is None else _lit_int(num_node)
    return None


def _is_jit_expr(expr: ast.AST) -> bool:
    """Does this expression mention jax.jit (directly or through partial)?"""
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr == "jit":
            return True
        if isinstance(n, ast.Name) and n.id == "jit":
            return True
    return False


def _is_jit_call(call: ast.Call) -> bool:
    """A DIRECT ``jax.jit(...)`` / ``jit(...)`` call — not a chained
    ``jax.jit(fn).lower(...)`` whose args are abstract values, not traced
    functions."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "jit") or (
        isinstance(f, ast.Name) and f.id == "jit"
    )


def _local_bindings(fn_node: ast.AST) -> Set[str]:
    """Names bound inside a function (params + assignments + comprehension
    targets): references to these are data flow, not module-function refs."""
    out: Set[str] = set()
    args = fn_node.args
    for a in (
        list(args.posonlyargs)
        + list(args.args)
        + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        out.add(a.arg)
    for n in ast.walk(fn_node):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.For, ast.comprehension)):
            targets = (
                n.targets
                if isinstance(n, ast.Assign)
                else [getattr(n, "target", None)]
            )
            for t in targets:
                if t is None:
                    continue
                for x in ast.walk(t):
                    if isinstance(x, ast.Name):
                        out.add(x.id)
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            for x in ast.walk(n.optional_vars):
                if isinstance(x, ast.Name):
                    out.add(x.id)
        elif (
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
            and n is not fn_node
        ):
            # nested defs bind their name locally; references to them are
            # covered by the nested-def propagation rule, and resolving the
            # bare name module-wide would drag in unrelated same-name defs
            out.add(n.name)
    return out


class _Linter:
    def __init__(self, root: pathlib.Path, files: List[pathlib.Path]):
        self.root = root
        self.modules: Dict[str, _ModuleIndex] = {}
        for f in files:
            rel = str(f.relative_to(root))
            try:
                self.modules[rel] = _ModuleIndex(f, rel, root)
            except SyntaxError as e:  # pragma: no cover - repo code parses
                raise RuntimeError(f"tpulint: cannot parse {rel}: {e}") from e
        self.findings: List[Finding] = []

    # ---- pass 1: function tables + traced roots --------------------------

    def index_functions(self):
        for rel, mod in self.modules.items():
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = _FuncInfo(module=rel, name=node.name, node=node)
                    mod.functions.setdefault(node.name, []).append(info)

    def resolve(self, mod: _ModuleIndex, node: ast.AST) -> List[_FuncInfo]:
        """Resolve a Name / module-attr reference to package functions."""
        if isinstance(node, ast.Name):
            # imported symbols win over same-named local defs: a function-
            # local `from models.base import forward` shadows a module-level
            # method named `forward` at its use sites
            if node.id in mod.import_symbols:
                target_mod, name = mod.import_symbols[node.id]
                target = self.modules.get(target_mod)
                if target:
                    return target.functions.get(name, [])
            if node.id in mod.functions:
                return mod.functions[node.id]
        elif isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            target_rel = mod.import_modules.get(node.value.id)
            target = self.modules.get(target_rel) if target_rel else None
            if target:
                return target.functions.get(node.attr, [])
        return []

    def seed_traced(self):
        for rel, mod in self.modules.items():
            for infos in mod.functions.values():
                for info in infos:
                    for dec in getattr(info.node, "decorator_list", []):
                        if _is_jit_expr(dec):
                            info.traced = True
            def mark_expr(expr, seen):
                for ref in _names_in(expr):
                    for target in self.resolve(mod, ref):
                        target.traced = True
                    # chase `name = <expr>` one assignment at a time so
                    # `step = partial(forward, ...); jax.jit(step)` seeds
                    # `forward` (cycle-guarded via `seen`)
                    if isinstance(ref, ast.Name) and ref.id not in seen:
                        seen.add(ref.id)
                        for rhs in mod.assignments.get(ref.id, []):
                            mark_expr(rhs, seen)

            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call) and _is_jit_call(node)):
                    continue
                # every function referenced anywhere in the jit call's args
                # is (transitively) a traced root
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    mark_expr(arg, set())

    def collect_refs(self):
        for rel, mod in self.modules.items():
            for infos in mod.functions.values():
                for info in infos:
                    local = _local_bindings(info.node)
                    for n in ast.walk(info.node):
                        if isinstance(n, ast.Call):
                            for ref in _names_in(n.func) + [
                                r
                                for a in list(n.args) + [k.value for k in n.keywords]
                                for r in _names_in(a)
                            ]:
                                if isinstance(ref, ast.Name) and ref.id in local:
                                    continue
                                for t in self.resolve(mod, ref):
                                    info.refs.add((t.module, t.name))

    def propagate_traced(self):
        changed = True
        while changed:
            changed = False
            for mod in self.modules.values():
                for infos in mod.functions.values():
                    for info in infos:
                        if not info.traced:
                            continue
                        # nested defs of a traced function are traced
                        for n in ast.walk(info.node):
                            if isinstance(
                                n, (ast.FunctionDef, ast.AsyncFunctionDef)
                            ) and n is not info.node:
                                for cand in mod.functions.get(n.name, []):
                                    if cand.node is n and not cand.traced:
                                        cand.traced = True
                                        changed = True
                        for tm, tn in info.refs:
                            target = self.modules.get(tm)
                            if not target:
                                continue
                            for cand in target.functions.get(tn, []):
                                if not cand.traced:
                                    cand.traced = True
                                    changed = True

    def traced_functions(self) -> List[Tuple[_ModuleIndex, _FuncInfo]]:
        out = []
        for mod in self.modules.values():
            for infos in mod.functions.values():
                for info in infos:
                    if info.traced:
                        out.append((mod, info))
        return out

    # ---- pass 2: rules ---------------------------------------------------

    def _emit(self, mod, node, rule, severity, message, def_line=None, key=None):
        line = getattr(node, "lineno", 0)
        if mod.suppressed(line, rule, def_line):
            return
        self.findings.append(
            Finding(
                rule=rule,
                severity=severity,
                location=f"{mod.relpath}:{line}",
                message=message,
                key=key if key is not None else mod.relpath,
            )
        )

    def rule_host_sync_census(self):
        for mod in self.modules.values():
            # [(bucket label, note, [(line_lo, line_hi), ...]), ...] — a
            # file may pin several independent buckets (router.py pins the
            # placement loop AND the hand-off path)
            hot_buckets = []
            for suffix, buckets in HOT_PATH_BUCKETS.items():
                if not mod.relpath.endswith(suffix):
                    continue
                for label, names, note in buckets:
                    ranges = []
                    for name, infos in mod.functions.items():
                        if name not in names:
                            continue
                        for info in infos:
                            node = info.node
                            ranges.append(
                                (node.lineno,
                                 getattr(node, "end_lineno", node.lineno))
                            )
                    hot_buckets.append((label, note, ranges))
                    # a renamed/removed hot-path function must not silently
                    # disarm the gate (the baseline only fails on count
                    # INCREASES, so a bucket quietly dropping to 0 is
                    # invisible) — a stale name is a loud, non-baselined
                    # error instead
                    for name in sorted(names - set(mod.functions)):
                        self._emit(
                            mod, mod.tree, "TPU102", SEV_ERROR,
                            f"the {label} census names `{name}` but {suffix} "
                            f"defines no such function — the hot-path census "
                            f"is stale (a renamed per-step method would "
                            f"silently escape the gate); update the set in "
                            f"analysis/tpulint.py",
                            key=f"{mod.relpath}::{label}-stale",
                        )
            for n in ast.walk(mod.tree):
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                name = None
                if isinstance(f, ast.Attribute) and f.attr in (
                    "device_get",
                    "block_until_ready",
                ):
                    name = f.attr
                elif isinstance(f, ast.Name) and f.id in (
                    "device_get",
                    "block_until_ready",
                ):
                    # `from jax import device_get; device_get(x)` must not
                    # slip past the pinned census
                    name = f.id
                if not name:
                    continue
                self._emit(
                    mod, n, "TPU102", SEV_WARNING,
                    f"host-sync call `{name}` (census; the baseline pins "
                    f"this file's count — batch fetches into one "
                    f"device_get per step)",
                )
                line = getattr(n, "lineno", 0)
                for bucket, hot_note, ranges in hot_buckets:
                    if not any(a <= line <= b for a, b in ranges):
                        continue
                    # separately-pinned bucket per HOT_PATH_BUCKETS: a NEW
                    # blocking fetch inside step/route/handoff-reachable
                    # code trips this gate even if the per-file count is
                    # rebalanced elsewhere in the file (ISSUE 8/10/15; the
                    # pipelined ragged path consumes via np.asarray on an
                    # async-copied array, deliberately NOT a census name).
                    self._emit(
                        mod, n, "TPU102", SEV_WARNING,
                        f"host-sync call `{name}` inside the {bucket} "
                        f"functions (separately-pinned census bucket — "
                        f"{hot_note})",
                        key=f"{mod.relpath}::{bucket}",
                    )

    def _body_nodes(self, info: _FuncInfo):
        """Nodes of this function body, excluding nested defs (they are
        linted as their own traced functions)."""
        nested = [
            n
            for n in ast.walk(info.node)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not info.node
        ]
        skip = set()
        for nd in nested:
            skip.update(id(x) for x in ast.walk(nd))
            skip.discard(id(nd))
        for n in ast.walk(info.node):
            if id(n) not in skip:
                yield n

    def rule_under_trace(self):
        for mod, info in self.traced_functions():
            def_line = info.node.lineno
            for n in self._body_nodes(info):
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                if isinstance(f, ast.Attribute):
                    if f.attr in HOST_SYNC_ATTRS:
                        # dict.items() etc. have different names; `.item()` on
                        # anything inside a traced body is the bug
                        self._emit(
                            mod, n, "TPU101", SEV_ERROR,
                            f"host-sync `.{f.attr}(...)` inside jit-traced "
                            f"`{info.name}` — forces a device round-trip/"
                            f"concretization at trace time; move it to the "
                            f"host loop",
                            def_line=def_line,
                        )
                    elif (
                        isinstance(f.value, ast.Name)
                        and f.value.id in ("time",)
                        and f.attr in HOST_TIME_FUNCS
                    ):
                        self._emit(
                            mod, n, "TPU103", SEV_ERROR,
                            f"`time.{f.attr}()` inside jit-traced "
                            f"`{info.name}` — executes once at trace time; "
                            f"use utils/profiling.py host-side",
                            def_line=def_line,
                        )
                    elif (
                        isinstance(f.value, ast.Name)
                        and f.value.id in ("np", "numpy")
                        and f.attr in NP_SYNC_FUNCS
                    ):
                        self._emit(
                            mod, n, "TPU106", SEV_WARNING,
                            f"`np.{f.attr}` inside jit-traced `{info.name}` — "
                            f"fine on trace-time constants (baseline/pragma "
                            f"it), a sync or crash on traced values",
                            def_line=def_line,
                        )
                elif isinstance(f, ast.Name) and f.id == "print":
                    self._emit(
                        mod, n, "TPU103", SEV_ERROR,
                        f"`print` inside jit-traced `{info.name}` — runs once "
                        f"at trace time; use jax.debug.print",
                        def_line=def_line,
                    )
                elif isinstance(f, ast.Name) and f.id in (
                    "device_get",
                    "block_until_ready",
                ):
                    # bare-imported forms of the host-sync calls
                    self._emit(
                        mod, n, "TPU101", SEV_ERROR,
                        f"host-sync `{f.id}(...)` inside jit-traced "
                        f"`{info.name}` — forces a device round-trip/"
                        f"concretization at trace time; move it to the "
                        f"host loop",
                        def_line=def_line,
                    )

    def rule_telemetry_under_trace(self):
        """TPU107: no metric recording under a jit trace. Two detectors:
        references to symbols imported from the telemetry package (resolved
        through the import maps), and bare ``.inc(...)``/``.observe(...)``
        metric-mutator calls (the heuristic half for sessions reached
        through attributes the import map cannot see)."""
        for mod, info in self.traced_functions():
            def_line = info.node.lineno
            local = _local_bindings(info.node)
            for n in self._body_nodes(info):
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute) and f.attr in METRIC_RECORD_ATTRS:
                        self._emit(
                            mod, n, "TPU107", SEV_ERROR,
                            f"metric `.{f.attr}(...)` inside jit-traced "
                            f"`{info.name}` — Python under trace runs once "
                            f"per compile, so this records compiles, not "
                            f"steps; record in the host loop on the step's "
                            f"existing batched fetch",
                            def_line=def_line,
                        )
                if isinstance(n, ast.Name) and n.id not in local:
                    tgt = mod.import_symbols.get(n.id)
                    if tgt and tgt[0].startswith(TELEMETRY_PKG):
                        self._emit(
                            mod, n, "TPU107", SEV_ERROR,
                            f"telemetry symbol `{n.id}` referenced inside "
                            f"jit-traced `{info.name}` — recording (or even "
                            f"resolving a session) belongs in host loops "
                            f"only; under trace it runs once and lies",
                            def_line=def_line,
                        )
                elif isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name):
                    rel = mod.import_modules.get(n.value.id)
                    if rel and rel.startswith(TELEMETRY_PKG):
                        self._emit(
                            mod, n, "TPU107", SEV_ERROR,
                            f"telemetry module access "
                            f"`{n.value.id}.{n.attr}` inside jit-traced "
                            f"`{info.name}` — recording belongs in host "
                            f"loops only; under trace it runs once and lies",
                            def_line=def_line,
                        )

    def rule_large_unsharded_constants(self):
        """TPU108: statically-sized jnp array creation ≥ the element
        threshold inside a traced body, with no sharding wrapper anywhere
        above it in the expression."""
        for mod, info in self.traced_functions():
            def_line = info.node.lineno
            wrapped: Set[int] = set()
            for n in self._body_nodes(info):
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
                if name in SHARDING_WRAPPERS:
                    for sub in ast.walk(n):
                        wrapped.add(id(sub))
            for n in self._body_nodes(info):
                if not isinstance(n, ast.Call) or id(n) in wrapped:
                    continue
                f = n.func
                if not (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "jnp"
                    and f.attr in JNP_ARRAY_CREATORS
                ):
                    continue
                count = _static_elem_count(n)
                if count is None or count < TPU108_ELEM_THRESHOLD:
                    continue
                self._emit(
                    mod, n, "TPU108", SEV_WARNING,
                    f"`jnp.{f.attr}` creates {count} elements inside "
                    f"jit-traced `{info.name}` with no sharding constraint — "
                    f"GSPMD replicates unconstrained constants, so this "
                    f"costs model-group× its HBM; wrap it in "
                    f"with_sharding_constraint (or build it host-side and "
                    f"device_put it sharded)",
                    def_line=def_line,
                )

    def rule_module_mutable_state(self):
        """TPU109: a module-level dict/list/set in runtime/ written from any
        function in the module — shared state with no owning object, i.e.
        nothing the concurrency audit's confinement census can classify."""
        for mod in self.modules.values():
            if not mod.relpath.startswith(TPU109_SCOPE_PREFIX):
                continue
            mutables: Set[str] = set()
            for node in mod.tree.body:
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [node.target]
                else:
                    continue
                v = node.value
                is_mutable = isinstance(
                    v, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp)
                )
                if isinstance(v, ast.Call):
                    fn = v.func
                    name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", None)
                    is_mutable = is_mutable or name in MUTABLE_CONSTRUCTORS
                if not is_mutable:
                    continue
                for t in targets:
                    if isinstance(t, ast.Name):
                        mutables.add(t.id)
            if not mutables:
                continue
            for infos in mod.functions.values():
                for info in infos:
                    # names bound as PLAIN locals (params / bare-Name
                    # assignments / loop targets). _local_bindings is the
                    # wrong tool here: it walks subscript-assignment
                    # targets too, so `REGISTRY[k] = v` would mark REGISTRY
                    # itself local and hide exactly the write this rule
                    # exists to catch.
                    local: Set[str] = set()
                    args = info.node.args
                    for a in (
                        list(args.posonlyargs) + list(args.args)
                        + list(args.kwonlyargs)
                        + ([args.vararg] if args.vararg else [])
                        + ([args.kwarg] if args.kwarg else [])
                    ):
                        local.add(a.arg)
                    declared_global: Set[str] = set()
                    for n in self._body_nodes(info):
                        if isinstance(n, ast.Global):
                            declared_global.update(n.names)
                        elif isinstance(n, ast.Assign):
                            for t in n.targets:
                                if isinstance(t, ast.Name):
                                    local.add(t.id)
                        elif isinstance(n, (ast.AnnAssign, ast.NamedExpr)):
                            # `x: Dict = {}` / `(x := ...)` bind locals
                            # exactly like a plain assignment
                            if isinstance(n.target, ast.Name):
                                local.add(n.target.id)
                        elif isinstance(n, (ast.For, ast.comprehension)):
                            for x in ast.walk(n.target):
                                if isinstance(x, ast.Name):
                                    local.add(x.id)
                        elif isinstance(n, ast.withitem) and n.optional_vars:
                            for x in ast.walk(n.optional_vars):
                                if isinstance(x, ast.Name):
                                    local.add(x.id)
                    local -= declared_global

                    def emit(n, name, how, info=info):
                        self._emit(
                            mod, n, "TPU109", SEV_WARNING,
                            f"module-level mutable `{name}` (assigned at "
                            f"import time) is written from `{info.name}` "
                            f"({how}) — hidden shared state with no owning "
                            f"object: no thread-confinement argument covers "
                            f"it (CONC601 census), and under "
                            f"thread-per-replica router stepping it is a "
                            f"cross-replica race; move it onto an owning "
                            f"class or suppress with a justification",
                            def_line=info.node.lineno,
                            key=f"{mod.relpath}::{name}",
                        )

                    for n in self._body_nodes(info):
                        if isinstance(n, (ast.Assign, ast.AugAssign)):
                            tgts = (
                                n.targets if isinstance(n, ast.Assign)
                                else [n.target]
                            )
                            for t in tgts:
                                if (
                                    isinstance(t, ast.Subscript)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id in mutables
                                    and t.value.id not in local
                                ):
                                    emit(n, t.value.id, "subscript assignment")
                                elif (
                                    isinstance(t, ast.Name)
                                    and t.id in mutables
                                    and t.id in declared_global
                                ):
                                    emit(n, t.id, "global rebind")
                        elif isinstance(n, ast.Call) and isinstance(
                            n.func, ast.Attribute
                        ):
                            recv = n.func.value
                            if (
                                n.func.attr in CONTAINER_MUTATORS
                                and isinstance(recv, ast.Name)
                                and recv.id in mutables
                                and recv.id not in local
                            ):
                                emit(n, recv.id, f".{n.func.attr}() call")

    def rule_pallas_interpret(self):
        for mod in self.modules.values():
            for n in ast.walk(mod.tree):
                if not isinstance(n, ast.Call):
                    continue
                f = n.func
                is_pallas = (isinstance(f, ast.Name) and f.id == "pallas_call") or (
                    isinstance(f, ast.Attribute) and f.attr == "pallas_call"
                )
                if not is_pallas:
                    continue
                if not any(k.arg == "interpret" for k in n.keywords):
                    self._emit(
                        mod, n, "TPU104", SEV_ERROR,
                        "`pallas_call` without `interpret=` — every kernel "
                        "must plumb ops/kernel_mode.kernel_interpret() so the "
                        "CPU mesh can run it and the AOT lowering tests can "
                        "force-compile it",
                    )

    def rule_mutable_defaults(self):
        for mod in self.modules.values():
            for infos in mod.functions.values():
                for info in infos:
                    args = info.node.args
                    for default in list(args.defaults) + [
                        d for d in args.kw_defaults if d is not None
                    ]:
                        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                            self._emit(
                                mod, default, "TPU105", SEV_ERROR,
                                f"mutable default argument in `{info.name}` — "
                                f"shared across calls; use None + in-body "
                                f"default",
                                def_line=info.node.lineno,
                            )

    def rule_silent_swallow(self):
        """TPU110: `except: pass` / `except Exception: pass` in runtime/ or
        telemetry/ — a silently swallowed failure on a serving or
        observability path."""
        for mod in self.modules.values():
            if not (
                "runtime/" in mod.relpath or "telemetry/" in mod.relpath
            ):
                continue
            for n in ast.walk(mod.tree):
                if not isinstance(n, ast.ExceptHandler):
                    continue
                broad = n.type is None or (
                    isinstance(n.type, ast.Name)
                    and n.type.id in ("Exception", "BaseException")
                )
                silent = all(
                    isinstance(s, ast.Pass)
                    or (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant))
                    for s in n.body
                )
                if broad and silent:
                    what = (
                        n.type.id if isinstance(n.type, ast.Name)
                        else "bare except"
                    )
                    self._emit(
                        mod, n, "TPU110", SEV_WARNING,
                        f"silent-swallow `except {what}: pass` — a broad "
                        f"catch that discards the failure hides leaks and "
                        f"corruption on a runtime/telemetry path; catch the "
                        f"typed class or re-raise",
                        key=f"{mod.relpath}::silent-swallow",
                    )

    def run(self) -> List[Finding]:
        self.index_functions()
        self.seed_traced()
        self.collect_refs()
        self.propagate_traced()
        self.rule_under_trace()
        self.rule_telemetry_under_trace()
        self.rule_large_unsharded_constants()
        self.rule_host_sync_census()
        self.rule_pallas_interpret()
        self.rule_mutable_defaults()
        self.rule_module_mutable_state()
        self.rule_silent_swallow()
        self.findings.sort(key=lambda f: (f.location, f.rule))
        return self.findings


def package_files(root: Optional[pathlib.Path] = None) -> Tuple[pathlib.Path, List[pathlib.Path]]:
    """(repo root, package .py files). The analysis package itself is linted
    too — it must obey its own rules."""
    if root is None:
        root = pathlib.Path(__file__).resolve().parents[2]
    pkg = root / PACKAGE
    return root, sorted(pkg.rglob("*.py"))


def run(root: Optional[pathlib.Path] = None, files: Optional[List[pathlib.Path]] = None) -> List[Finding]:
    """Lint the package (or an explicit file list, for fixture tests)."""
    resolved_root, pkg_files = package_files(root)
    if files is not None:
        pkg_files = files
    return _Linter(resolved_root, pkg_files).run()


def lint_paths(paths: List[pathlib.Path], root: pathlib.Path) -> List[Finding]:
    """Lint arbitrary snippet files (test fixtures) relative to ``root``."""
    return _Linter(root, [p.resolve() for p in paths]).run()
