"""Lifecycle & resource-stewardship analyzer (LIFE8xx): every resource a
request acquires is provably released on every terminal outcome.

The serving stack's containment story (PRs 7/10/15) is a RELEASE story: a
typed failure degrades to one failed request because ``_finish`` /
``_preempt`` / ``harvest`` give back everything the request owned — its
serving slot, its KV blocks, its prefix-cache refcounts, its hand-off
payload, its telemetry span. The concurrency audit (PR 13) pins WHO may
write shared state; nothing pinned WHETHER every acquisition reaches a
paired release. That gap is exactly where elastic fleet changes (grow/
shrink replicas mid-run — ROADMAP "Elastic fleet") would leak, so — in the
PR-13 tradition of shipping the analyzer first — this suite proves resource
stewardship over the AST + traced call graph and pins the census to
``analysis/life_baseline.json``:

- **LIFE801 acquire/release pairing census** — every acquisition site in
  scope is mined and classified by resource: serving-slot assignment
  (``self.slots[i] = req``), KV block allocation (``alloc_seq``),
  prefix-cache refcount acquisition (``match_prefix``/``commit_seq``),
  hand-off payload extraction (``extract_request_kv``), telemetry span open
  (``tel.span(...)``). Gate (zero error budget): a module with acquisitions
  of a resource must carry paired release sites (``slots[i] = None``,
  ``free_seq``/``quarantine_seq``, ``inject_request_kv``); every terminal
  handler (a function assigning STATUS_FINISHED/STATUS_FAILED) and the
  preemption handler must REACH a slot release over the traced call graph;
  refcount mutation sites must be symmetric (ref sites without unref sites
  — or the reverse — is an error); a ``.span(...)`` opened outside a
  ``with`` leaks the open span on any raise. The acquire/release site
  census is baseline-pinned: a new acquisition site is reviewed like a new
  collective.
- **LIFE802 request state-machine extraction** — every ``<req>.status =
  STATUS_*`` / ``RSTATUS_*`` transition (including consts passed through
  ``_terminal``-style helpers) is mined into a pinned (state, function)
  census. Checks: terminal states (FINISHED/FAILED) are assigned only by
  functions that reach a slot release (the terminal-releases-everything
  invariant); re-activation transitions (ACTIVE/WAITING/QUEUED/PLACED) may
  happen ONLY inside the validated doors (``_admit``,
  ``add_prefilled_request``, ``_preempt``, ``_readmit_preempted``,
  ``_failover_request``, ``_place_pending``) — a transition out of a
  terminal state anywhere else is an error. REJECTED is the door-side
  verdict (no resources held yet) and carries no release obligation.
- **LIFE803 exception-flow audit** — every ``raise`` reachable from a
  worker/step entry (``ReplicaHandle.step``, ``_ReplicaStepWorker.run``,
  the sessions' ``step``) must be caught at a TYPED boundary somewhere in
  the worker-reachable set (``except RuntimeError``, ``except
  RETRYABLE_DISPATCH_ERRORS`` — broad ``except Exception`` /
  ``BaseException`` handlers are transport, not boundaries, and do not
  count) or sit on the loud-failure allowlist (``WatchdogError``,
  ``RetraceError`` — designed to propagate with a diagnostic snapshot).
  A silent-swallow handler (``except:``/``except Exception:`` whose body is
  only ``pass``) in runtime/ is an error outright (tpulint TPU110 carries
  the warning-level version for telemetry/).
- **LIFE804 thread/server lifecycle** — every ``Thread.start()`` site
  (``_ReplicaStepWorker`` self-start, the ``OpsServer`` serve thread) must
  have a matching ``join()`` reachable from a close/context-exit path
  (``close``/``stop``/``shutdown``/``__exit__``) — an unjoined thread
  outlives its owner and leaks.
- **LIFE805 replica-death ownership transfer** — the harvest paths provably
  release or re-queue everything a dead (or retiring) replica owned:
  ``_failover_replica`` must reach ``harvest`` AND ``_failover_request``;
  ``harvest`` must clear ``owned``/``_placed_t``/``_readmit``;
  ``_fail_total_outage`` must reach ``_failover_replica``; the elastic
  primitives are licensed here — ``retire_replica`` must reach the
  finalizer and the finalizer must reach the worker ``shutdown`` (join),
  ``add_replica`` must reach ``_place_pending`` (a warmed handle that never
  joins placement is dead weight).

Like the other suites: ``python -m neuronx_distributed_inference_tpu.analysis
--suites life`` exits 0 on a clean tree, ``--write-baseline`` regenerates
``life_baseline.json`` and prints the unified diff, and the ``--json``
report carries a ``"lifecycle"`` section with the stewardship breakdown.
Suppression: ``# life: ignore[LIFE801]`` on the offending line or its
``def`` line. See docs/STATIC_ANALYSIS.md "Lifecycle audit".
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from neuronx_distributed_inference_tpu.analysis.findings import (
    Baseline,
    Finding,
    SEV_ERROR,
    SEV_WARNING,
)

PACKAGE = "neuronx_distributed_inference_tpu"
BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "life_baseline.json"

#: the audited surface — the request/replica lifecycle layers, matched by
#: relpath suffix so fixture trees audit identically
SCOPE_SUFFIXES = (
    "runtime/serving.py",
    "runtime/router.py",
    "runtime/replica.py",
    "runtime/faults.py",
    "runtime/disaggregated.py",
    # the allocator: refcount symmetry (LIFE801) is proven where the
    # refcounts live
    "modules/block_kvcache.py",
    "telemetry/ops_server.py",
)

#: worker/step entry points for the LIFE803 reachability walk — the code a
#: replica's step (threaded or not) actually runs
WORKER_ENTRIES = (
    ("ReplicaHandle", "step"),
    ("_ReplicaStepWorker", "run"),
    ("ServingSession", "step"),
    ("SpeculativeServingSession", "step"),
)

#: exceptions DESIGNED to propagate loudly out of a step: diagnostic
#: snapshot attached / retrace contract violation / unsupported-config
#: contract guard (NotImplementedError is the Python convention for "this
#: path must fail loudly, never be handled")
LOUD_ALLOWLIST = frozenset({
    "WatchdogError", "RetraceError", "NotImplementedError",
})

#: tuple-alias except clauses expanded to their member classes (the typed
#: retry boundaries of runtime/faults.py and runtime/router.py)
EXC_TUPLE_ALIASES = {
    "RETRYABLE_DISPATCH_ERRORS": (
        "TransientDispatchError", "JaxRuntimeError", "XlaRuntimeError",
    ),
    "_HANDOFF_RETRYABLE": (
        "HandoffTransitError", "TransientDispatchError", "JaxRuntimeError",
        "XlaRuntimeError",
    ),
}

#: state constants mined into the LIFE802 machine
SESSION_TERMINAL = frozenset({"STATUS_FINISHED", "STATUS_FAILED"})
SESSION_REJECT = frozenset({"STATUS_REJECTED"})
ROUTER_TERMINAL = frozenset({"RSTATUS_FINISHED", "RSTATUS_FAILED"})
ROUTER_REJECT = frozenset({"RSTATUS_REJECTED"})
REACTIVATION = frozenset({
    "STATUS_ACTIVE", "STATUS_WAITING", "RSTATUS_QUEUED", "RSTATUS_PLACED",
})
STATE_CONSTS = (
    SESSION_TERMINAL | SESSION_REJECT | ROUTER_TERMINAL | ROUTER_REJECT
    | REACTIVATION
)

#: the validated doors: the ONLY functions that may move a request back to
#: a live state (admission, re-admission after preemption, failover
#: re-queue, placement binding). Everything else re-activating a request is
#: a transition out of a terminal state the analyzer cannot prove guarded.
REACTIVATION_DOORS = frozenset({
    "_admit", "add_prefilled_request", "_preempt", "_readmit_preempted",
    "_failover_request", "_place_pending", "__init__", "__post_init__",
})

#: terminal handlers exempt from the release-reach obligation: the door
#: verdict — the request was never admitted, so it holds nothing
RELEASE_EXEMPT_FUNCS = frozenset({"_reject"})

#: LIFE805 ownership-transfer reach obligations, enforced whenever the
#: source function exists in the audited set (fixtures without it skip).
#: The elastic primitives (ServingRouter.add_replica / retire_replica) are
#: licensed by the last three entries.
REQUIRED_REACH = (
    (("ServingRouter", "_failover_replica"), ("ReplicaHandle", "harvest"),
     "a dead replica's owned requests are never harvested"),
    (("ServingRouter", "_failover_replica"),
     ("ServingRouter", "_failover_request"),
     "harvested requests are never re-queued to the survivors"),
    (("ServingRouter", "_fail_total_outage"),
     ("ServingRouter", "_failover_replica"),
     "a total outage strands dead replicas' owned requests"),
    (("ServingRouter", "retire_replica"),
     ("ServingRouter", "_finalize_retired"),
     "a retiring replica is never finalized (mesh + worker leak)"),
    (("ServingRouter", "_finalize_retired"),
     ("_ReplicaStepWorker", "shutdown"),
     "scale-in never joins the retired replica's worker thread"),
    (("ServingRouter", "add_replica"), ("ServingRouter", "_place_pending"),
     "a newly added replica never joins placement"),
)

#: attributes ``ReplicaHandle.harvest`` must clear — the dead replica's
#: ownership ledger; anything left behind is orphaned state
HARVEST_MUST_CLEAR = ("owned", "_placed_t", "_readmit")

#: close/context-exit roots for the LIFE804 join-reachability walk
CLOSE_ROOTS = frozenset({"close", "stop", "shutdown", "__exit__"})

_PRAGMA_RE = re.compile(r"#\s*life:\s*ignore(?:\[([A-Z0-9, ]+)\])?")

#: set by :func:`run` — the stewardship breakdown the CLI embeds in --json
_LAST_REPORT: Dict = {}


# ---------------------------------------------------------------------------
# module / function indexing (lean sibling of the concurrency audit's)
# ---------------------------------------------------------------------------


@dataclass(eq=False)  # identity semantics: _Func instances key dicts/sets
class _Func:
    module: str  # scope-relative path (matched suffix)
    cls: str  # "" for module-level functions
    name: str
    node: ast.AST
    bases: Tuple[str, ...] = ()
    calls: Set[Tuple[str, str]] = field(default_factory=set)  # (cls, name)
    worker: bool = False  # reachable from a WORKER_ENTRY

    @property
    def key(self) -> Tuple[str, str]:
        return (self.cls, self.name)

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class _Module:
    def __init__(self, path: pathlib.Path, scope_rel: str):
        self.path = path
        self.rel = scope_rel
        self.source = path.read_text()
        self.tree = ast.parse(self.source, filename=str(path))
        self.pragmas = self._collect_pragmas()

    def _collect_pragmas(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.source.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                rules = m.group(1)
                out[i] = {r.strip() for r in rules.split(",")} if rules else {"*"}
        return out

    def suppressed(self, line: int, rule: str, def_line: Optional[int] = None) -> bool:
        for ln in (line, def_line):
            if ln is None:
                continue
            rules = self.pragmas.get(ln)
            if rules and ("*" in rules or rule in rules):
                return True
        return False


def _base_names(node: ast.ClassDef) -> Tuple[str, ...]:
    """Base names, ``threading.Thread``-style attribute bases included (by
    their terminal attr) — LIFE804 needs Thread subclasses recognized."""
    out = []
    for b in node.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return tuple(out)


class _Analyzer:
    def __init__(self, files: List[Tuple[pathlib.Path, str]]):
        self.modules: List[_Module] = [_Module(p, rel) for p, rel in files]
        self.findings: List[Finding] = []
        self.class_bases: Dict[str, Tuple[str, ...]] = {}
        self.methods: Dict[Tuple[str, str], List[_Func]] = {}
        self.funcs: List[_Func] = []
        # (cls, attr) of attributes assigned a Thread(...) instance
        self.thread_attrs: Set[Tuple[str, str]] = set()
        self._index()
        self._build_calls()
        self._mark_worker_set()

    # ---- indexing --------------------------------------------------------

    def _index(self):
        for mod in self.modules:
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    bases = _base_names(node)
                    self.class_bases[node.name] = bases
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            self._add_func(mod, node.name, sub, bases)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_func(mod, "", node, ())
        # thread-holding attributes: self.<attr> = threading.Thread(...)
        for f in self.funcs:
            if not f.cls:
                continue
            for n in ast.walk(f.node):
                if not (isinstance(n, ast.Assign) and isinstance(n.value, ast.Call)):
                    continue
                v = n.value.func
                name = v.attr if isinstance(v, ast.Attribute) else (
                    v.id if isinstance(v, ast.Name) else None
                )
                if name != "Thread" and name not in self._thread_subclasses():
                    continue
                for t in n.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.thread_attrs.add((f.cls, t.attr))

    def _thread_subclasses(self) -> Set[str]:
        out = set()
        for cls in self.class_bases:
            if "Thread" in self._hierarchy_up(cls):
                out.add(cls)
        return out

    def _hierarchy_up(self, cls: str) -> Set[str]:
        """cls + transitive base names (in-scope bases expand; others — like
        ``Thread`` — stay as leaf names)."""
        out = {cls}
        frontier = [cls]
        while frontier:
            c = frontier.pop()
            for b in self.class_bases.get(c, ()):
                if b not in out:
                    out.add(b)
                    frontier.append(b)
        return out

    def _hierarchy(self, cls: str) -> Set[str]:
        """cls + in-scope bases + in-scope subclasses (method resolution
        fans out over the hierarchy — the conservative direction)."""
        out = self._hierarchy_up(cls)
        changed = True
        while changed:
            changed = False
            for c, bases in self.class_bases.items():
                if c not in out and any(b in out for b in bases):
                    out.add(c)
                    changed = True
        return out

    def _add_func(self, mod: _Module, cls: str, node, bases):
        f = _Func(module=mod.rel, cls=cls, name=node.name, node=node, bases=bases)
        f._mod = mod  # type: ignore[attr-defined]
        self.funcs.append(f)
        self.methods.setdefault((cls, node.name), []).append(f)
        # nested defs (dispatch closures): their own functions in the same
        # class context, with an implicit call edge from the parent
        for sub in ast.walk(node):
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub is not node
            ):
                nf = _Func(module=mod.rel, cls=cls, name=sub.name, node=sub,
                           bases=bases)
                nf._mod = mod  # type: ignore[attr-defined]
                self.funcs.append(nf)
                self.methods.setdefault((cls, sub.name), []).append(nf)
                f.calls.add((cls, sub.name))

    # ---- call graph ------------------------------------------------------

    def _build_calls(self):
        by_name: Dict[str, List[Tuple[str, str]]] = {}
        for (cls, name) in self.methods:
            if cls:
                by_name.setdefault(name, []).append((cls, name))
        for f in self.funcs:
            for n in ast.walk(f.node):
                if not isinstance(n, ast.Call):
                    continue
                fn = n.func
                if isinstance(fn, ast.Name):
                    if ("", fn.id) in self.methods:
                        f.calls.add(("", fn.id))
                    continue
                if not isinstance(fn, ast.Attribute):
                    continue
                m = fn.attr
                recv = fn.value
                if isinstance(recv, ast.Name) and recv.id == "self" and f.cls:
                    hit = False
                    for c in self._hierarchy(f.cls):
                        if (c, m) in self.methods:
                            f.calls.add((c, m))
                            hit = True
                    if hit:
                        continue
                # receiver of unknown type: fan out to every same-named
                # in-scope method when the candidate set is small — the
                # conservative direction for reach obligations (`h.harvest()`
                # must find ReplicaHandle.harvest without a type checker)
                cands = by_name.get(m, [])
                if 1 <= len(cands) <= 6:
                    f.calls.update(cands)

    def _reachable(self, seeds: List[Tuple[str, str]]) -> Set[int]:
        seen: Set[int] = set()
        frontier: List[_Func] = []
        for key in seeds:
            for g in self.methods.get(key, []):
                if id(g) not in seen:
                    seen.add(id(g))
                    frontier.append(g)
        while frontier:
            g = frontier.pop()
            for key in g.calls:
                for h in self.methods.get(key, []):
                    if id(h) not in seen:
                        seen.add(id(h))
                        frontier.append(h)
        return seen

    def _mark_worker_set(self):
        for fid in self._reachable(list(WORKER_ENTRIES)):
            pass  # ids only; mark via second pass below
        worker_ids = self._reachable(list(WORKER_ENTRIES))
        for f in self.funcs:
            if id(f) in worker_ids:
                f.worker = True

    def _func_reaches(self, src: _Func, dst: Tuple[str, str]) -> bool:
        targets = {id(g) for g in self.methods.get(dst, [])}
        return bool(targets & self._reachable([src.key])) or src.key == dst

    # ---- emission --------------------------------------------------------

    def _emit(self, f: _Func, node, rule, severity, message, key):
        line = getattr(node, "lineno", 0)
        mod: _Module = f._mod  # type: ignore[attr-defined]
        if mod.suppressed(line, rule, getattr(f.node, "lineno", None)):
            return
        self.findings.append(Finding(
            rule=rule, severity=severity,
            location=f"{f.module}:{line}", message=message, key=key,
        ))

    # ---- LIFE801: acquire/release pairing census -------------------------

    @staticmethod
    def _call_attr(n) -> Optional[str]:
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute):
                return n.func.attr
            if isinstance(n.func, ast.Name):
                return n.func.id
        return None

    def _resource_sites(self, f: _Func):
        """Yield (node, resource, kind) for acquire/release sites in f.
        kind is 'acquire' | 'release'."""
        with_items = set()
        for n in ast.walk(f.node):
            if isinstance(n, ast.With):
                for item in n.items:
                    with_items.add(id(item.context_expr))
        for n in ast.walk(f.node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr == "slots"
                    ):
                        is_none = (
                            isinstance(n.value, ast.Constant)
                            and n.value.value is None
                        )
                        yield (n, "slot", "release" if is_none else "acquire")
            attr = self._call_attr(n)
            if attr is None:
                continue
            if attr == "alloc_seq":
                yield (n, "kv_blocks", "acquire")
            elif attr in ("free_seq", "quarantine_seq"):
                yield (n, "kv_blocks", "release")
                yield (n, "prefix_ref", "release")
            elif attr in ("match_prefix", "commit_seq"):
                yield (n, "prefix_ref", "acquire")
            elif attr == "extract_request_kv":
                yield (n, "handoff_payload", "acquire")
            elif attr == "inject_request_kv":
                yield (n, "handoff_payload", "release")
            elif attr == "span" and isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute
            ):
                if id(n) in with_items:
                    yield (n, "span", "acquire")
                else:
                    yield (n, "span", "unscoped")

    def _refcount_sites(self, f: _Func):
        """Yield (node, 'ref'|'unref') for refcount-table mutations."""
        for n in ast.walk(f.node):
            if isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Subscript
            ) and isinstance(n.target.value, ast.Attribute) and (
                n.target.value.attr == "refcount"
            ):
                yield (n, "ref" if isinstance(n.op, ast.Add) else "unref")
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr == "refcount"
                        and isinstance(n.value, ast.BinOp)
                    ):
                        yield (
                            n,
                            "ref" if isinstance(n.value.op, ast.Add) else "unref",
                        )
            elif isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute
            ) and n.func.attr == "pop" and isinstance(
                n.func.value, ast.Attribute
            ) and n.func.value.attr == "refcount":
                yield (n, "unref")

    def rule_pairing(self, transitions):
        by_module_res: Dict[Tuple[str, str], Dict[str, int]] = {}
        for f in self.funcs:
            for node, res, kind in self._resource_sites(f):
                if kind == "unscoped":
                    self._emit(
                        f, node, "LIFE801", SEV_ERROR,
                        f"`.span(...)` opened outside a `with` in `{f.qual}` "
                        f"— a raise before close leaks the open span (every "
                        f"span site must be a `with tel.span(...)` item)",
                        key=f"{f.module}::span-no-with",
                    )
                    continue
                d = by_module_res.setdefault((f.module, res), {})
                d[kind] = d.get(kind, 0) + 1
                self._emit(
                    f, node, "LIFE801", SEV_WARNING,
                    f"resource census: {res} {kind} in `{f.qual}`",
                    key=f"{f.module}::{res}-{kind}::{f.qual}",
                )
        # per-module pairing: acquisitions demand release sites. The
        # hand-off payload pairs across modules (extracted tier-side,
        # injected decode-side), so its zero-release check only applies
        # when the injecting module (runtime/serving.py) is in the audited
        # set — single-file fixtures of the extract side stay clean.
        mods_audited = {m.rel for m in self.modules}
        for (module, res), d in sorted(by_module_res.items()):
            if res == "span":
                continue
            if d.get("acquire") and not d.get("release"):
                if res == "handoff_payload":
                    if "runtime/serving.py" not in mods_audited:
                        continue
                    released_anywhere = any(
                        dd.get("release")
                        for (_m, r), dd in by_module_res.items()
                        if r == res
                    )
                    if released_anywhere:
                        continue
                mod = next(m for m in self.modules if m.rel == module)
                self.findings.append(Finding(
                    rule="LIFE801", severity=SEV_ERROR,
                    location=f"{module}:0",
                    message=(
                        f"leaked {res}: {module} acquires {res} "
                        f"({d['acquire']} site(s)) but carries no paired "
                        f"release site — every terminal outcome must give "
                        f"the resource back"
                    ),
                    key=f"{module}::{res}-unreleased",
                ))
        # terminal/preempt handlers must REACH a slot release
        release_funcs = set()
        for f in self.funcs:
            for _node, res, kind in self._resource_sites(f):
                if res == "slot" and kind == "release":
                    release_funcs.add(f.key)
        for f, consts in transitions.items():
            if f.name in RELEASE_EXEMPT_FUNCS:
                continue
            if not (consts & SESSION_TERMINAL):
                continue
            mod_has_slots = any(
                ff.module == f.module and any(
                    r == "slot" for _n, r, k in self._resource_sites(ff)
                )
                for ff in self.funcs
            )
            if not mod_has_slots:
                continue
            reach = self._reachable([f.key]) | {id(g) for g in
                                               self.methods.get(f.key, [])}
            hit = any(
                id(g) in reach
                for key in release_funcs
                for g in self.methods.get(key, [])
            )
            if not hit:
                self._emit(
                    f, f.node, "LIFE801", SEV_ERROR,
                    f"leaked slot: terminal handler `{f.qual}` assigns a "
                    f"terminal status but never reaches a slot release "
                    f"(`slots[i] = None`) — the terminal outcome strands "
                    f"the request's serving slot",
                    key=f"{f.module}::terminal-no-release::{f.qual}",
                )
        # refcount symmetry
        refs: Dict[str, Dict[str, int]] = {}
        for f in self.funcs:
            for node, kind in self._refcount_sites(f):
                d = refs.setdefault(f.module, {})
                d[kind] = d.get(kind, 0) + 1
                self._emit(
                    f, node, "LIFE801", SEV_WARNING,
                    f"refcount census: {kind} site in `{f.qual}`",
                    key=f"{f.module}::refcount-{kind}::{f.qual}",
                )
        for module, d in sorted(refs.items()):
            if d.get("ref") and not d.get("unref"):
                self.findings.append(Finding(
                    rule="LIFE801", severity=SEV_ERROR, location=f"{module}:0",
                    message=(
                        f"unpaired ref: {module} increments prefix-cache "
                        f"refcounts ({d['ref']} site(s)) with no decrement "
                        f"site — shared blocks can never recycle"
                    ),
                    key=f"{module}::refcount-unpaired-ref",
                ))
            elif d.get("unref") and not d.get("ref"):
                self.findings.append(Finding(
                    rule="LIFE801", severity=SEV_ERROR, location=f"{module}:0",
                    message=(
                        f"unpaired unref: {module} decrements prefix-cache "
                        f"refcounts ({d['unref']} site(s)) with no increment "
                        f"site — refcounts go negative and evict live blocks"
                    ),
                    key=f"{module}::refcount-unpaired-unref",
                ))
        self._refcount_totals = {
            "ref_sites": sum(d.get("ref", 0) for d in refs.values()),
            "unref_sites": sum(d.get("unref", 0) for d in refs.values()),
        }
        self._resource_totals = {}
        for (_m, res), d in by_module_res.items():
            tot = self._resource_totals.setdefault(
                res, {"acquire": 0, "release": 0}
            )
            for kind in ("acquire", "release"):
                tot[kind] += d.get(kind, 0)

    # ---- LIFE802: state-machine extraction -------------------------------

    def _mine_transitions(self) -> Dict[_Func, Set[str]]:
        """(function -> state consts it assigns or passes to a terminal
        helper). Also emits the pinned (state, function) census."""
        out: Dict[_Func, Set[str]] = {}
        for f in self.funcs:
            consts: Set[str] = set()
            sites: List[Tuple[ast.AST, str]] = []
            for n in ast.walk(f.node):
                if isinstance(n, ast.Assign) and isinstance(
                    n.value, ast.Name
                ) and n.value.id in STATE_CONSTS:
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and t.attr == "status":
                            consts.add(n.value.id)
                            sites.append((n, n.value.id))
                elif isinstance(n, ast.Call):
                    for a in n.args:
                        if isinstance(a, ast.Name) and a.id in STATE_CONSTS:
                            consts.add(a.id)
                            sites.append((n, a.id))
            if consts:
                out[f] = consts
                for node, const in sites:
                    self._emit(
                        f, node, "LIFE802", SEV_WARNING,
                        f"state transition census: -> {const} in `{f.qual}`",
                        key=f"{f.module}::{const}::{f.qual}",
                    )
        return out

    def rule_state_machine(self, transitions: Dict[_Func, Set[str]]):
        for f, consts in transitions.items():
            live = consts & REACTIVATION
            if live and f.name not in REACTIVATION_DOORS:
                self._emit(
                    f, f.node, "LIFE802", SEV_ERROR,
                    f"`{f.qual}` re-activates a request "
                    f"({', '.join(sorted(live))}) outside the validated "
                    f"doors ({', '.join(sorted(REACTIVATION_DOORS - {'__init__', '__post_init__'}))}) "
                    f"— a transition out of a terminal state cannot be "
                    f"proven guarded; re-admission must re-enter through "
                    f"the door",
                    key=f"{f.module}::reactivation-outside-door::{f.qual}",
                )
        self._state_totals: Dict[str, int] = {}
        for consts in transitions.values():
            for c in consts:
                self._state_totals[c] = self._state_totals.get(c, 0) + 1

    # ---- LIFE803: exception-flow audit -----------------------------------

    def _exc_class_bases(self, name: str) -> Set[str]:
        return self._hierarchy_up(name) if name in self.class_bases else {name}

    def rule_exception_flow(self):
        catchable: Set[str] = set()
        for f in self.funcs:
            if not f.worker:
                continue
            for n in ast.walk(f.node):
                if not isinstance(n, ast.ExceptHandler):
                    continue
                names = []
                t = n.type
                elts = t.elts if isinstance(t, ast.Tuple) else ([t] if t else [])
                for e in elts:
                    if isinstance(e, ast.Name):
                        names.append(e.id)
                    elif isinstance(e, ast.Attribute):
                        names.append(e.attr)
                for name in names:
                    if name in ("Exception", "BaseException"):
                        continue  # transport, not a typed boundary
                    catchable.update(EXC_TUPLE_ALIASES.get(name, (name,)))
        for f in self.funcs:
            # silent swallow: broad except whose body is only pass — an
            # error in runtime/ regardless of worker reachability
            mod_rel = f.module
            for n in ast.walk(f.node):
                if isinstance(n, ast.ExceptHandler) and mod_rel.startswith(
                    "runtime/"
                ):
                    broad = n.type is None or (
                        isinstance(n.type, ast.Name)
                        and n.type.id in ("Exception", "BaseException")
                    )
                    silent = all(
                        isinstance(s, ast.Pass)
                        or (isinstance(s, ast.Expr)
                            and isinstance(s.value, ast.Constant))
                        for s in n.body
                    )
                    if broad and silent:
                        self._emit(
                            f, n, "LIFE803", SEV_ERROR,
                            f"silent-swallow `except{': ' + n.type.id if isinstance(n.type, ast.Name) else ''}: pass` "
                            f"in `{f.qual}` — a swallowed failure on a "
                            f"runtime path is an invisible leak; catch the "
                            f"typed class or let it propagate loudly",
                            key=f"{mod_rel}::silent-swallow",
                        )
            if not f.worker:
                continue
            for n in ast.walk(f.node):
                if not isinstance(n, ast.Raise):
                    continue
                if n.exc is None:
                    self._emit(
                        f, n, "LIFE803", SEV_WARNING,
                        f"raise census: re-raise in `{f.qual}`",
                        key=f"{f.module}::reraise::{f.qual}",
                    )
                    continue
                exc = n.exc
                cname = None
                if isinstance(exc, ast.Call):
                    fn = exc.func
                    cname = fn.id if isinstance(fn, ast.Name) else (
                        fn.attr if isinstance(fn, ast.Attribute) else None
                    )
                elif isinstance(exc, ast.Name):
                    cname = exc.id
                if cname is None:
                    continue
                if cname[:1].islower() and cname not in self.class_bases:
                    # `raise err`: re-raising a transported/caught exception
                    # object (the worker->router barrier pattern), not a new
                    # failure origin — the origin was classified at its own
                    # raise site
                    self._emit(
                        f, n, "LIFE803", SEV_WARNING,
                        f"raise census: transported re-raise `{cname}` in "
                        f"`{f.qual}`",
                        key=f"{f.module}::reraise::{f.qual}",
                    )
                    continue
                if cname in LOUD_ALLOWLIST:
                    self._emit(
                        f, n, "LIFE803", SEV_WARNING,
                        f"raise census: loud `{cname}` in `{f.qual}` "
                        f"(designed to propagate)",
                        key=f"{f.module}::loud::{cname}::{f.qual}",
                    )
                    continue
                if self._exc_class_bases(cname) & catchable:
                    self._emit(
                        f, n, "LIFE803", SEV_WARNING,
                        f"raise census: `{cname}` in `{f.qual}` caught at a "
                        f"typed boundary",
                        key=f"{f.module}::caught::{cname}::{f.qual}",
                    )
                    continue
                self._emit(
                    f, n, "LIFE803", SEV_ERROR,
                    f"uncaught worker raise: `{cname}` in `{f.qual}` is "
                    f"reachable from a worker/step entry but no typed "
                    f"boundary in the worker-reachable set catches it and "
                    f"it is not on the loud-failure allowlist "
                    f"({', '.join(sorted(LOUD_ALLOWLIST))}) — it would "
                    f"tear down the replica thread mid-step",
                    key=f"{f.module}::uncaught::{cname}::{f.qual}",
                )

    # ---- LIFE804: thread/server lifecycle --------------------------------

    def _thread_start_sites(self, f: _Func):
        """Yield (node, identity) for Thread start() calls; identity is
        ('class', cls) for Thread-subclass self-starts and ('attr', attr)
        for stored thread objects."""
        threads = self._thread_subclasses()
        for n in ast.walk(f.node):
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)):
                continue
            if n.func.attr != "start":
                continue
            recv = n.func.value
            if isinstance(recv, ast.Name) and recv.id == "self" and f.cls in threads:
                yield (n, ("class", f.cls))
            elif (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and (f.cls, recv.attr) in self.thread_attrs
            ):
                yield (n, ("attr", recv.attr))

    def _join_identities(self, f: _Func) -> Set[Tuple[str, str]]:
        # locals aliasing self-attributes (`thread = self._thread`, incl.
        # tuple unpacking) count as joins of the aliased attribute
        alias: Dict[str, str] = {}
        for n in ast.walk(f.node):
            if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                continue
            t, v = n.targets[0], n.value
            pairs = []
            if isinstance(t, ast.Tuple) and isinstance(v, ast.Tuple) and len(
                t.elts
            ) == len(v.elts):
                pairs = list(zip(t.elts, v.elts))
            else:
                pairs = [(t, v)]
            for tt, vv in pairs:
                if (
                    isinstance(tt, ast.Name)
                    and isinstance(vv, ast.Attribute)
                    and isinstance(vv.value, ast.Name)
                    and vv.value.id == "self"
                ):
                    alias[tt.id] = vv.attr
        out: Set[Tuple[str, str]] = set()
        threads = self._thread_subclasses()
        for n in ast.walk(f.node):
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)):
                continue
            if n.func.attr != "join":
                continue
            recv = n.func.value
            if isinstance(recv, ast.Name) and recv.id == "self" and f.cls in threads:
                out.add(("class", f.cls))
            elif isinstance(recv, ast.Attribute):
                out.add(("attr", recv.attr))
            elif isinstance(recv, ast.Name):
                if recv.id in alias:
                    out.add(("attr", alias[recv.id]))
                out.add(("var", recv.id))
        return out

    def rule_thread_lifecycle(self):
        close_seeds = [k for k in self.methods if k[1] in CLOSE_ROOTS]
        close_reach = self._reachable(close_seeds)
        joined: Set[Tuple[str, str]] = set()
        for f in self.funcs:
            if id(f) not in close_reach:
                continue
            joined |= self._join_identities(f)
        n_starts = 0
        for f in self.funcs:
            for node, ident in self._thread_start_sites(f):
                n_starts += 1
                self._emit(
                    f, node, "LIFE804", SEV_WARNING,
                    f"thread census: start of {ident[1]} in `{f.qual}`",
                    key=f"{f.module}::thread-start::{ident[1]}",
                )
                if ident not in joined:
                    self._emit(
                        f, node, "LIFE804", SEV_ERROR,
                        f"unjoined thread: `{ident[1]}` started in "
                        f"`{f.qual}` has no `join()` reachable from a "
                        f"close/stop/shutdown/__exit__ path — the thread "
                        f"outlives its owner (leak on every teardown)",
                        key=f"{f.module}::thread-unjoined::{ident[1]}",
                    )
        self._thread_starts = n_starts

    # ---- LIFE805: replica-death ownership transfer -----------------------

    def rule_ownership_transfer(self):
        passed: List[str] = []
        for src_key, dst_key, why in REQUIRED_REACH:
            srcs = self.methods.get(src_key, [])
            if not srcs:
                continue
            label = (
                f"{src_key[0]}.{src_key[1]}->{dst_key[0]}.{dst_key[1]}"
            )
            for src in srcs:
                if self._func_reaches(src, dst_key):
                    passed.append(label)
                else:
                    self._emit(
                        src, src.node, "LIFE805", SEV_ERROR,
                        f"ownership transfer broken: `{src.qual}` never "
                        f"reaches `{dst_key[0]}.{dst_key[1]}` — {why}",
                        key=f"{src.module}::reach::{label}",
                    )
        self._reach_passed = sorted(set(passed))
        # harvest must clear the whole ownership ledger
        for f in self.methods.get(("ReplicaHandle", "harvest"), []):
            cleared = set()
            for n in ast.walk(f.node):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "clear"
                    and isinstance(n.func.value, ast.Attribute)
                ):
                    cleared.add(n.func.value.attr)
            for attr in HARVEST_MUST_CLEAR:
                if attr not in cleared:
                    self._emit(
                        f, f.node, "LIFE805", SEV_ERROR,
                        f"orphaned dead-replica state: `{f.qual}` does not "
                        f"clear `{attr}` — the dead replica's ledger keeps "
                        f"rows the router believes were transferred",
                        key=f"{f.module}::harvest-keeps::{attr}",
                    )

    # ---- driver ----------------------------------------------------------

    def run(self) -> List[Finding]:
        transitions = self._mine_transitions()
        self.rule_pairing(transitions)
        self.rule_state_machine(transitions)
        self.rule_exception_flow()
        self.rule_thread_lifecycle()
        self.rule_ownership_transfer()
        self.findings.sort(key=lambda f: (f.rule, f.key, f.location))
        return self.findings


# ---------------------------------------------------------------------------
# entry points (mirrors the concurrency audit's shape)
# ---------------------------------------------------------------------------


def _scope_files(root: Optional[pathlib.Path] = None) -> List[Tuple[pathlib.Path, str]]:
    if root is None:
        root = pathlib.Path(__file__).resolve().parents[2]
    pkg = root / PACKAGE
    out = []
    for suffix in SCOPE_SUFFIXES:
        p = pkg / suffix
        if p.is_file():
            out.append((p, suffix))
    return out


def _match_scope(path: pathlib.Path) -> Optional[str]:
    s = str(path)
    for suffix in SCOPE_SUFFIXES:
        if s.endswith(suffix):
            return suffix
    # fixture fallback: match by basename so tmp-dir snippets audit as the
    # file they stand in for
    for suffix in SCOPE_SUFFIXES:
        if path.name == pathlib.Path(suffix).name:
            return suffix
    return None


def audit_paths(paths: List[pathlib.Path]) -> List[Finding]:
    """Audit arbitrary snippet files (test fixtures): each file is scoped by
    suffix/basename match against :data:`SCOPE_SUFFIXES` and the RAW
    findings (census entries included, no baseline filtering) come back."""
    files = []
    for p in paths:
        rel = _match_scope(p)
        if rel is None:
            raise ValueError(
                f"{p}: not a recognizable scope file (expected one of "
                f"{SCOPE_SUFFIXES} by suffix or basename)"
            )
        files.append((p, rel))
    return _Analyzer(files).run()


def _build_report(an: _Analyzer, findings: List[Finding]) -> Dict:
    census: Dict[str, int] = {}
    errors = 0
    raises = {"caught": 0, "loud": 0, "reraise": 0}
    for f in findings:
        if f.severity == SEV_ERROR:
            errors += 1
            continue
        census[f.key] = census.get(f.key, 0) + 1
        if f.rule == "LIFE803":
            kind = f.key.split("::", 2)[1]
            if kind in raises:
                raises[kind] += 1
    return {
        "errors": errors,
        "resources": getattr(an, "_resource_totals", {}),
        "refcount": getattr(an, "_refcount_totals", {}),
        "states": dict(sorted(getattr(an, "_state_totals", {}).items())),
        "raises": raises,
        "thread_starts": getattr(an, "_thread_starts", 0),
        "reach_checks": getattr(an, "_reach_passed", []),
        "census": dict(sorted(census.items())),
        "worker_entries": [f"{c}.{m}" for c, m in WORKER_ENTRIES],
    }


def last_report() -> Dict:
    return _LAST_REPORT


def render_breakdown(report: Optional[Dict] = None) -> str:
    rep = report if report is not None else _LAST_REPORT
    if not rep:
        return ""
    res = rep.get("resources", {})
    lines = [
        "lifecycle resource-stewardship census "
        f"({sum(d.get('acquire', 0) for d in res.values())} acquire / "
        f"{sum(d.get('release', 0) for d in res.values())} release sites; "
        f"worker entries: {', '.join(rep['worker_entries'])}):"
    ]
    for name, d in sorted(res.items()):
        lines.append(
            f"  {name:>16}: {d.get('acquire', 0)} acquire / "
            f"{d.get('release', 0)} release"
        )
    rc = rep.get("refcount", {})
    if rc:
        lines.append(
            f"  refcount symmetry: {rc.get('ref_sites', 0)} ref / "
            f"{rc.get('unref_sites', 0)} unref sites"
        )
    rz = rep.get("raises", {})
    lines.append(
        f"  worker raises: {rz.get('caught', 0)} caught, "
        f"{rz.get('loud', 0)} loud, {rz.get('reraise', 0)} re-raise; "
        f"threads started/joined: {rep.get('thread_starts', 0)}"
    )
    if rep.get("reach_checks"):
        lines.append(
            "  ownership-transfer reach: " + ", ".join(rep["reach_checks"])
        )
    return "\n".join(lines)


def run(write_baseline: bool = False) -> List[Finding]:
    """Audit the real tree against ``life_baseline.json``; returns the NEW
    (gate-failing) findings. Errors (leaks, unpaired refs, uncaught worker
    raises, unjoined threads, broken ownership transfer) are never
    baselined — only the acquire/release, state and raise censuses are."""
    global _LAST_REPORT
    an = _Analyzer(_scope_files())
    findings = an.run()
    _LAST_REPORT = _build_report(an, findings)
    warnings = [f for f in findings if f.severity == SEV_WARNING]
    errors = [f for f in findings if f.severity == SEV_ERROR]
    if write_baseline:
        Baseline.from_findings(warnings).save(BASELINE_PATH)
        return errors
    return Baseline.load(BASELINE_PATH).filter_new(warnings) + errors
