"""CLI: ``python -m neuronx_distributed_inference_tpu.analysis``.

Thin module-entry shim — the parser, suite dispatch and baseline-diff logic
live in :mod:`.cli`, which ``scripts/run_static_analysis.py`` shares (one
arg parser, no flag drift between entry points).
"""

from __future__ import annotations

import sys

from neuronx_distributed_inference_tpu.analysis.cli import (  # noqa: F401
    ALL_SUITES,
    build_parser,
    main,
    run_suites,
)

if __name__ == "__main__":
    sys.exit(main())
