"""CLI: ``python -m neuronx_distributed_inference_tpu.analysis``.

Runs the analysis suites and exits non-zero when any NON-BASELINED finding
exists. Designed to run on a CPU-only host (``JAX_PLATFORMS=cpu``): the
graph audit traces tiny tp-sharded models on 8 virtual devices.

    python -m neuronx_distributed_inference_tpu.analysis            # text
    python -m neuronx_distributed_inference_tpu.analysis --json     # JSON
    python -m ... --suites lint,flags      # skip the (slower) graph audit
    python -m ... --write-baseline         # accept current findings/census
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from neuronx_distributed_inference_tpu.analysis import findings as findings_mod
from neuronx_distributed_inference_tpu.analysis.findings import Baseline, Finding

TPULINT_BASELINE = os.path.join(os.path.dirname(__file__), "tpulint_baseline.json")

ALL_SUITES = ("lint", "flags", "graph")


def _prepare_jax_cpu():
    """Force the CPU backend with 8 virtual devices (idempotent; a no-op if
    a backend is already initialized by the embedding process)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    import jax

    try:
        jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS") or "cpu")
    except Exception:
        pass


def run_suites(
    suites: List[str], write_baseline: bool = False
) -> tuple[List[Finding], List[Finding]]:
    """Run the requested suites; return (all findings, new findings)."""
    all_findings: List[Finding] = []
    baselined: List[Finding] = []  # findings subject to the tpulint baseline
    unbaselined: List[Finding] = []  # graph/flag findings: always new

    if "lint" in suites:
        from neuronx_distributed_inference_tpu.analysis import tpulint

        baselined.extend(tpulint.run())
    if "flags" in suites:
        from neuronx_distributed_inference_tpu.analysis import flag_audit

        unbaselined.extend(flag_audit.run())
    if "graph" in suites:
        _prepare_jax_cpu()
        from neuronx_distributed_inference_tpu.analysis import graph_audit

        unbaselined.extend(graph_audit.run(write_baseline=write_baseline))

    all_findings = baselined + unbaselined
    if write_baseline and "lint" in suites:
        Baseline.from_findings(baselined).save(TPULINT_BASELINE)
        new = list(unbaselined)
    else:
        new = Baseline.load(TPULINT_BASELINE).filter_new(baselined) + unbaselined
    return all_findings, new


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m neuronx_distributed_inference_tpu.analysis",
        description="Static-analysis gate: tpulint + flag audit + graph audit",
    )
    parser.add_argument("--json", action="store_true", help="JSON report")
    parser.add_argument(
        "--suites",
        default=",".join(ALL_SUITES),
        help=f"comma list of {ALL_SUITES} (default: all)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept current lint findings + graph census as the baseline",
    )
    args = parser.parse_args(argv)
    suites = [s.strip() for s in args.suites.split(",") if s.strip()]
    unknown = set(suites) - set(ALL_SUITES)
    if unknown:
        parser.error(f"unknown suite(s) {sorted(unknown)}; pick from {ALL_SUITES}")

    all_findings, new = run_suites(suites, write_baseline=args.write_baseline)
    print(findings_mod.render_report(all_findings, new, as_json=args.json, suites=suites))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
