"""One CLI for the static-analysis gate — shared by
``python -m neuronx_distributed_inference_tpu.analysis`` and
``scripts/run_static_analysis.py`` (one arg-parser, so the flag surface
cannot drift between the two entry points).

Runs the analysis suites and exits non-zero when any NON-BASELINED finding
exists. Designed to run on a CPU-only host (``JAX_PLATFORMS=cpu``): the
graph/shard/memory audits trace tiny tp-sharded models on 8 virtual devices.

    python -m neuronx_distributed_inference_tpu.analysis            # text
    python -m neuronx_distributed_inference_tpu.analysis --json     # JSON
    python -m ... --suites lint,flags      # skip the (slower) traced audits
    python -m ... --write-baseline         # accept current findings/censuses

An unknown ``--suites`` name is an ERROR (exit 2 with the known list) — a
typo must never select nothing and report green. ``--write-baseline`` prints
a unified diff of every baseline file it rewrote, so a regeneration is
reviewable right in the terminal before it is committed.
"""

from __future__ import annotations

import argparse
import difflib
import os
import sys
from typing import Dict, List, Optional, Tuple

from neuronx_distributed_inference_tpu.analysis import findings as findings_mod
from neuronx_distributed_inference_tpu.analysis.findings import Baseline, Finding

_ANALYSIS_DIR = os.path.dirname(__file__)
TPULINT_BASELINE = os.path.join(_ANALYSIS_DIR, "tpulint_baseline.json")

ALL_SUITES = (
    "lint", "flags", "graph", "shard", "memory", "cost", "conc", "kernel",
    "life",
)

#: every committed baseline file --write-baseline may rewrite (diffed after)
BASELINE_FILES = (
    "tpulint_baseline.json",
    "graph_baseline.json",
    "shard_baseline.json",
    "memory_baseline.json",
    "cost_baseline.json",
    "conc_baseline.json",
    "kernel_baseline.json",
    "tuning_table.json",
    "life_baseline.json",
)


def _prepare_jax_cpu():
    """Force the CPU backend with 8 virtual devices (idempotent; a no-op if
    a backend is already initialized by the embedding process)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    import jax

    try:
        jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS") or "cpu")
    except Exception:
        pass


def build_parser() -> argparse.ArgumentParser:
    """THE arg parser for the gate — both entry points consume it."""
    parser = argparse.ArgumentParser(
        prog="python -m neuronx_distributed_inference_tpu.analysis",
        description=(
            "Static-analysis gate: tpulint + flag audit + graph audit + "
            "shard audit + memory audit + cost audit + concurrency audit + "
            "kernel audit + lifecycle audit"
        ),
    )
    parser.add_argument("--json", action="store_true", help="JSON report")
    parser.add_argument(
        "--compare",
        metavar="BENCH_JSON",
        default=None,
        help=(
            "offline measured-vs-projected report over a committed bench "
            "summary (BENCH_rNN.json); prints per-row error and exits 0 — "
            "informational, no gate"
        ),
    )
    parser.add_argument(
        "--suites",
        default=",".join(ALL_SUITES),
        help=f"comma list of {ALL_SUITES} (default: all)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help=(
            "accept current lint findings + graph/shard/memory censuses as "
            "the baseline (prints a unified diff of every rewritten file)"
        ),
    )
    return parser


def parse_suites(parser: argparse.ArgumentParser, raw: str) -> List[str]:
    """Validate the --suites list: an unknown name errors with the known
    set (exit 2) instead of silently selecting nothing and passing."""
    suites = [s.strip() for s in raw.split(",") if s.strip()]
    unknown = set(suites) - set(ALL_SUITES)
    if unknown:
        parser.error(
            f"unknown suite(s) {sorted(unknown)}; known suites: "
            f"{', '.join(ALL_SUITES)}"
        )
    if not suites:
        parser.error(f"--suites selected nothing; known suites: {', '.join(ALL_SUITES)}")
    return suites


def run_suites(
    suites: List[str], write_baseline: bool = False
) -> Tuple[List[Finding], List[Finding], Dict]:
    """Run the requested suites; return (all findings, new findings,
    extras). ``extras`` carries suite-specific report payloads (the memory
    suite's per-bucket HBM breakdown) for the JSON/text report."""
    baselined: List[Finding] = []  # findings subject to the tpulint baseline
    unbaselined: List[Finding] = []  # graph/shard/memory/flag: always new
    extras: Dict = {}

    if "lint" in suites:
        from neuronx_distributed_inference_tpu.analysis import tpulint

        baselined.extend(tpulint.run())
    if "flags" in suites:
        from neuronx_distributed_inference_tpu.analysis import flag_audit

        unbaselined.extend(flag_audit.run())
    traced_suites = [
        s for s in ("graph", "shard", "memory", "cost", "kernel") if s in suites
    ]
    if traced_suites:
        _prepare_jax_cpu()
    if "graph" in suites:
        from neuronx_distributed_inference_tpu.analysis import graph_audit

        unbaselined.extend(graph_audit.run(write_baseline=write_baseline))
    if "shard" in suites:
        from neuronx_distributed_inference_tpu.analysis import shard_audit

        unbaselined.extend(shard_audit.run(write_baseline=write_baseline))
    if "memory" in suites:
        from neuronx_distributed_inference_tpu.analysis import memory_audit

        unbaselined.extend(memory_audit.run(write_baseline=write_baseline))
        extras["memory"] = memory_audit.last_report()
    if "cost" in suites:
        from neuronx_distributed_inference_tpu.analysis import cost_audit

        unbaselined.extend(cost_audit.run(write_baseline=write_baseline))
        extras["cost"] = cost_audit.last_report()
    if "conc" in suites:
        # pure-AST like lint: no tracing, runs in milliseconds
        from neuronx_distributed_inference_tpu.analysis import concurrency_audit

        unbaselined.extend(concurrency_audit.run(write_baseline=write_baseline))
        extras["concurrency"] = concurrency_audit.last_report()
    if "kernel" in suites:
        from neuronx_distributed_inference_tpu.analysis import kernel_audit

        unbaselined.extend(kernel_audit.run(write_baseline=write_baseline))
        extras["kernel"] = kernel_audit.last_report()
    if "life" in suites:
        # pure-AST like conc: no tracing, runs in milliseconds
        from neuronx_distributed_inference_tpu.analysis import lifecycle_audit

        unbaselined.extend(lifecycle_audit.run(write_baseline=write_baseline))
        extras["lifecycle"] = lifecycle_audit.last_report()

    all_findings = baselined + unbaselined
    if write_baseline and "lint" in suites:
        Baseline.from_findings(baselined).save(TPULINT_BASELINE)
        new = list(unbaselined)
    else:
        new = Baseline.load(TPULINT_BASELINE).filter_new(baselined) + unbaselined
    return all_findings, new, extras


def _read_baselines() -> Dict[str, str]:
    out = {}
    for name in BASELINE_FILES:
        path = os.path.join(_ANALYSIS_DIR, name)
        try:
            with open(path) as f:
                out[name] = f.read()
        except FileNotFoundError:
            out[name] = ""
    return out


def baseline_diffs(before: Dict[str, str], after: Dict[str, str]) -> str:
    """Unified diff of every baseline file a --write-baseline run rewrote —
    printed so the regeneration is reviewed like code."""
    chunks = []
    for name in BASELINE_FILES:
        old, new = before.get(name, ""), after.get(name, "")
        if old == new:
            continue
        diff = difflib.unified_diff(
            old.splitlines(keepends=True),
            new.splitlines(keepends=True),
            fromfile=f"a/analysis/{name}",
            tofile=f"b/analysis/{name}",
        )
        chunks.append("".join(diff))
    return "\n".join(chunks)


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.compare:
        # measured-vs-projected over a committed bench summary: hardware
        # session zero's comparison tool — informational, exit 0 on any
        # readable summary (an unreadable file is a usage error, like an
        # unknown --suites name). Standalone: silently ignoring a combined
        # --json/--suites/--write-baseline would look like the gate ran.
        if args.json or args.write_baseline or args.suites != ",".join(ALL_SUITES):
            parser.error(
                "--compare is a standalone report; it cannot be combined "
                "with --json, --suites or --write-baseline"
            )
        from neuronx_distributed_inference_tpu.analysis import device_model

        try:
            report = device_model.compare_report(args.compare)
        except (OSError, ValueError) as e:
            parser.error(f"--compare {args.compare}: {e}")
        print(report)
        return 0
    suites = parse_suites(parser, args.suites)

    before = _read_baselines() if args.write_baseline else None
    all_findings, new, extras = run_suites(suites, write_baseline=args.write_baseline)

    extras_chunks = []
    if "memory" in extras:
        from neuronx_distributed_inference_tpu.analysis import memory_audit

        extras_chunks.append(memory_audit.render_breakdown(extras["memory"]))
    if "cost" in extras:
        from neuronx_distributed_inference_tpu.analysis import cost_audit

        extras_chunks.append(cost_audit.render_breakdown(extras["cost"]))
    if "concurrency" in extras:
        from neuronx_distributed_inference_tpu.analysis import concurrency_audit

        extras_chunks.append(
            concurrency_audit.render_breakdown(extras["concurrency"])
        )
    if "kernel" in extras:
        from neuronx_distributed_inference_tpu.analysis import kernel_audit

        extras_chunks.append(kernel_audit.render_breakdown(extras["kernel"]))
    if "lifecycle" in extras:
        from neuronx_distributed_inference_tpu.analysis import lifecycle_audit

        extras_chunks.append(
            lifecycle_audit.render_breakdown(extras["lifecycle"])
        )
    extras_text = "\n".join(c for c in extras_chunks if c) or None
    print(
        findings_mod.render_report(
            all_findings, new, as_json=args.json, suites=suites,
            extras=extras or None, extras_text=extras_text,
        )
    )
    if args.write_baseline:
        diff = baseline_diffs(before, _read_baselines())
        if diff:
            print(
                "--write-baseline rewrote committed baselines; review this "
                "diff like code:\n" + diff,
                file=sys.stderr,
            )
    return 1 if new else 0
