"""Retrace guard: fail when steady-state decode re-traces/recompiles.

The stack's core guarantee is a FIXED set of ahead-of-time compiled programs
(PAPER.md: AOT trace + compile of fixed-shape sub-models). A silent retrace
in the decode loop — a drifting input dtype, a new pytree structure, an
accidentally-fresh closure — recompiles mid-serve and destroys the latency
model without changing any output.

Mechanism: the hot-loop jitted entry points — ``SubModelRunner``'s step and
multi-step decode programs and the fused-speculation/EAGLE CTE/TKG programs
— are wrapped with :func:`trace_marker`, whose Python body executes ONLY
while jax is tracing (a jit cache hit replays the compiled program without
entering Python). Auxiliary apps (medusa, mllama, whisper, flux, encoders)
jit their own programs unwrapped: a RetraceGuard around THOSE loops observes
nothing — wrap their fns with trace_marker first. So
"the marker ran" == "the jit cache missed" == "a new program is being
traced". Two consumers:

- :class:`RetraceGuard` — a context manager that records every trace inside
  its scope and (by default) raises :class:`RetraceError` on exit if any
  happened. Tests wrap a steady-state decode loop with it to prove zero
  recompiles after warmup.
- *Sealing* — ``SubModelRunner.seal()`` (driven by
  ``TpuConfig.retrace_guard`` or ``NXDI_TPU_RETRACE_GUARD=1`` after
  ``warmup()``) arms the per-runner flag so any later trace of a sealed
  program raises immediately, even outside a guard scope.
"""

from __future__ import annotations

import os
from typing import List, Optional

__all__ = [
    "RetraceError",
    "RetraceGuard",
    "add_trace_listener",
    "guard_enabled",
    "note_trace",
    "remove_trace_listener",
    "trace_marker",
]

_ACTIVE: List["RetraceGuard"] = []
# telemetry bridge: listeners called as fn(tag, sealed) on EVERY observed
# trace — including forbidden post-seal retraces, which are counted BEFORE
# the RetraceError raises so a steady-state recompile surfaces as an
# operable counter (nxdi_sealed_retrace_total) and not only an assertion.
# Kept as a plain callback list so this module never imports telemetry
# (note_trace executes at trace time; a static telemetry reference here
# would trip tpulint TPU107's recording-under-trace rule).
_LISTENERS: List = []


def add_trace_listener(fn) -> None:
    """Register ``fn(tag: str, sealed: bool)`` to observe every jit trace."""
    if fn not in _LISTENERS:
        _LISTENERS.append(fn)


def remove_trace_listener(fn) -> None:
    if fn in _LISTENERS:
        _LISTENERS.remove(fn)


class RetraceError(RuntimeError):
    """A jit-traced program re-traced where the contract forbids it."""


def guard_enabled(config=None) -> bool:
    """Config/env switch for post-warmup sealing (satisfied by either)."""
    if config is not None and getattr(config, "retrace_guard", False):
        return True
    return os.environ.get("NXDI_TPU_RETRACE_GUARD", "").lower() in ("1", "true")


def note_trace(tag: str, sealed: bool = False) -> None:
    """Record that the program ``tag`` is being traced right now.

    Called from INSIDE jitted function bodies, so it fires exactly once per
    jit cache miss. Raises when the owning runner is sealed; otherwise the
    trace is recorded into every active :class:`RetraceGuard`.
    """
    for g in _ACTIVE:
        g.traces.append(tag)
    for listener in _LISTENERS:
        listener(tag, sealed)
    if sealed:
        raise RetraceError(
            f"{tag}: jit re-trace after warmup()/seal() — a steady-state "
            f"recompile breaks the AOT latency contract. New input shape/"
            f"dtype/pytree reached a sealed program (or warmup missed a "
            f"bucket); run the jaxpr auditor "
            f"(python -m neuronx_distributed_inference_tpu.analysis) and "
            f"check the call that triggered this."
        )


def trace_marker(tag: str, fn, owner=None):
    """Wrap ``fn`` (the function handed to ``jax.jit``) so each trace calls
    :func:`note_trace`. ``owner`` is the runner whose ``_sealed`` attribute
    arms the hard-failure mode; the attribute is read at trace time so
    sealing after wrap works."""

    def wrapped(*args, **kwargs):
        note_trace(tag, sealed=bool(owner is not None and getattr(owner, "_sealed", False)))
        return fn(*args, **kwargs)

    return wrapped


class RetraceGuard:
    """Context manager: collect (and by default forbid) traces in scope.

    ``allowed`` traces are tolerated before failing — e.g. a test that
    expects exactly the first-call compile can pass ``allowed=1``.
    ``fail=False`` turns it into a pure observer (inspect ``.traces``).
    """

    def __init__(self, fail: bool = True, allowed: int = 0):
        self.fail = fail
        self.allowed = allowed
        self.traces: List[str] = []

    def __enter__(self) -> "RetraceGuard":
        _ACTIVE.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> Optional[bool]:
        _ACTIVE.remove(self)
        if exc_type is None and self.fail and len(self.traces) > self.allowed:
            raise RetraceError(
                f"{len(self.traces)} jit trace(s) inside a RetraceGuard scope "
                f"(allowed {self.allowed}): {self.traces} — steady-state "
                f"decode must reuse the warmed programs."
            )
        return None
