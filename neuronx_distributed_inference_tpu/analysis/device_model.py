"""TPU device-spec registry + the analytic roofline projection model.

One table of nameplate numbers (peak FLOP/s by dtype, HBM GB/s, ICI GB/s)
and one set of closed-form llama-shaped cost formulas, consumed by THREE
places so the repo has a single source of truth for "how fast should this
be":

- :mod:`.cost_audit` projects a lower-bound step time / tok/s for every
  audited (family, bucket) program from its HLO-derived FLOPs/bytes census;
- ``bench.py`` emits ``projected_tok_s`` / ``model_error_frac`` beside every
  measured row (the measured-vs-predicted hook hardware session zero
  validates);
- ``python -m neuronx_distributed_inference_tpu.analysis.device_model``
  prints the markdown projection tables committed in PERF.md — the
  hand-written estimates those tables replace are gone; regenerate, don't
  re-type.

The registry numbers are NAMEPLATE (vendor peak). Measured efficiency on
this stack is ~67–92% of nameplate depending on op mix (PERF.md rounds
2–5); projections here are therefore LOWER BOUNDS on time (upper bounds on
tok/s), which is exactly what a regression gate wants: a measured number
can approach the bound but a model change that moves the bound itself must
be reviewed.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, Optional

# ---------------------------------------------------------------------------
# device registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DeviceSpec:
    """Nameplate per-chip numbers. ``peak_flops`` is keyed by compute dtype
    (matmul operand dtype); fp32 on v5e-class chips runs the bf16x3 path at
    ~1/3 the bf16 rate (PERF.md round 6)."""

    name: str
    peak_flops: Dict[str, float]  # dtype -> FLOP/s
    hbm_bw: float  # bytes/s
    ici_bw: float  # bytes/s per chip (one direction)
    hbm_capacity: int  # bytes
    vmem_bytes: int  # per-core scoped VMEM a single Pallas kernel may hold

    def peak(self, dtype: str) -> float:
        return self.peak_flops.get(_canon_dtype(dtype), self.peak_flops["bfloat16"])

    @property
    def ridge_flops_per_byte(self) -> float:
        """bf16 arithmetic-intensity ridge point: programs above it are
        compute-bound, below it bandwidth-bound (COST504)."""
        return self.peak_flops["bfloat16"] / self.hbm_bw


def _canon_dtype(dtype: str) -> str:
    d = str(dtype).lower()
    if d in ("bf16", "bfloat16"):
        return "bfloat16"
    if d in ("f32", "float32"):
        return "float32"
    if d.startswith("int8") or d.startswith("fp8") or d.startswith("float8"):
        return "int8"
    return d


#: per-chip nameplate specs. v5e matches the numbers every PERF.md roofline
#: already uses (197 TFLOP/s bf16, 819 GB/s HBM); the others are the public
#: vendor peaks — correct them from measurements if a hardware session
#: disagrees (the cost baselines pin FLOPs/bytes, not these constants).
#:
#: ``vmem_bytes`` is the per-core scoped-VMEM budget a single Pallas kernel
#: invocation can hold (operand windows + scratch), i.e. the compiler's
#: scoped-vmem limit (16 MiB class per the Pallas guide; Mosaic's
#: ``vmem_limit_bytes`` default). v6e carries the doubled Trillium on-chip
#: memory. KERN701 budgets against DEFAULT_DEVICE, so the v5e figure is the
#: binding one — keep it conservative and let a hardware session raise it.
DEVICE_REGISTRY: Dict[str, DeviceSpec] = {
    "v5e": DeviceSpec(
        name="v5e",
        peak_flops={"bfloat16": 197e12, "int8": 394e12, "float32": 197e12 / 3},
        hbm_bw=819e9,
        ici_bw=200e9,  # 1600 Gbps
        hbm_capacity=16 * 1024**3,
        vmem_bytes=16 * 1024**2,  # 16 MiB/core scoped VMEM (+128 KiB SMEM)
    ),
    "v5p": DeviceSpec(
        name="v5p",
        peak_flops={"bfloat16": 459e12, "int8": 918e12, "float32": 459e12 / 3},
        hbm_bw=2765e9,
        ici_bw=600e9,  # 4800 Gbps
        hbm_capacity=95 * 1024**3,
        vmem_bytes=16 * 1024**2,  # 16 MiB/core scoped VMEM
    ),
    "v6e": DeviceSpec(
        name="v6e",
        peak_flops={"bfloat16": 918e12, "int8": 1836e12, "float32": 918e12 / 3},
        hbm_bw=1640e9,
        ici_bw=448e9,  # 3584 Gbps
        hbm_capacity=32 * 1024**3,
        vmem_bytes=32 * 1024**2,  # Trillium doubles per-core on-chip memory
    ),
    "v4": DeviceSpec(
        name="v4",
        peak_flops={"bfloat16": 275e12, "int8": 275e12, "float32": 275e12 / 3},
        hbm_bw=1228e9,
        ici_bw=300e9,  # 2400 Gbps
        hbm_capacity=32 * 1024**3,
        vmem_bytes=16 * 1024**2,  # 16 MiB VMEM/core (+128 MiB chip CMEM)
    ),
}

#: the bench's target chip — projections on a host with no resolvable TPU
#: (the CPU harness) are computed against this spec with model_error_frac
#: left null (bench contract, tests/test_bench_smoke.py)
DEFAULT_DEVICE = "v5e"

_KIND_PATTERNS = (
    # substrings of jax's device_kind / str(device), most specific first
    ("v5 lite", "v5e"),
    ("v5e", "v5e"),
    ("v6 lite", "v6e"),
    ("v6e", "v6e"),
    ("v5p", "v5p"),
    ("v5", "v5p"),  # bare "TPU v5" is the p variant; lite matched above
    ("v4", "v4"),
)


def resolve_device(device_kind: str) -> Optional[DeviceSpec]:
    """Map a jax ``device_kind``/``str(device)`` (e.g. ``"TPU v5 lite0"``)
    to a registry spec; None for CPU/unknown devices (the caller then
    projects against :data:`DEFAULT_DEVICE` and reports no model error)."""
    kind = (device_kind or "").lower()
    if "tpu" not in kind and not kind.startswith("v"):
        return None
    for pat, name in _KIND_PATTERNS:
        if pat in kind:
            return DEVICE_REGISTRY[name]
    return None


def get_device(name: str = DEFAULT_DEVICE) -> DeviceSpec:
    return DEVICE_REGISTRY[name]


# ---------------------------------------------------------------------------
# model shapes (bench.py imports these — one definition)
# ---------------------------------------------------------------------------

LLAMA_1B = dict(
    model_type="llama",
    hidden_size=2048,
    intermediate_size=8192,
    num_attention_heads=32,
    num_key_value_heads=8,
    num_hidden_layers=16,
    vocab_size=128256,
    rms_norm_eps=1e-5,
    rope_theta=500000.0,
    max_position_embeddings=2048,
    hidden_act="silu",
    tie_word_embeddings=True,
    head_dim=64,
)

LLAMA_8B = dict(
    model_type="llama",
    hidden_size=4096,
    intermediate_size=14336,
    num_attention_heads=32,
    num_key_value_heads=8,
    num_hidden_layers=32,
    vocab_size=128256,
    rms_norm_eps=1e-5,
    rope_theta=500000.0,
    max_position_embeddings=2048,
    hidden_act="silu",
    tie_word_embeddings=False,
    head_dim=128,
)


def _itemsize(dtype: str) -> float:
    # int4: packed grouped codes (ops/quant_matmul) — 0.5 byte/param plus
    # one f32 scale per 128-group per out channel (4/128 byte/param), folded
    # in so the projection charges what the decode stream actually reads
    return {"bfloat16": 2, "int8": 1, "float32": 4, "int4": 0.5 + 4 / 128}[
        _canon_dtype(dtype)
    ]


def matmul_params(attrs: dict) -> Dict[str, int]:
    """Matmul-weight element counts of a llama-shaped model — the weights a
    decode step must stream from HBM (embedding is a gather, not a stream;
    tied-embedding models materialize a separate (H, V) lm_head at load, so
    lm_head always streams)."""
    H = attrs["hidden_size"]
    I = attrs["intermediate_size"]
    nq = attrs["num_attention_heads"]
    nkv = attrs["num_key_value_heads"]
    D = attrs.get("head_dim") or H // nq
    L = attrs["num_hidden_layers"]
    V = attrs["vocab_size"]
    per_layer = H * nq * D + 2 * H * nkv * D + nq * D * H + 3 * H * I
    return {
        "per_layer": per_layer,
        "layers_total": per_layer * L,
        "lm_head": H * V,
        "total": per_layer * L + H * V,
    }


def kv_bytes_per_token(attrs: dict, kv_dtype: str = "bfloat16") -> float:
    """Cache bytes one token occupies across all layers (K + V), codes only
    — the per-(layer, head) scales of a quantized cache are O(L·H) floats,
    noise next to the code stream."""
    nkv = attrs["num_key_value_heads"]
    D = attrs.get("head_dim") or attrs["hidden_size"] // attrs["num_attention_heads"]
    L = attrs["num_hidden_layers"]
    return 2 * L * nkv * D * _itemsize(kv_dtype)


def decode_projection(
    attrs: dict,
    *,
    batch: int,
    kv_width: int,
    weight_dtype: str = "bfloat16",
    kv_dtype: str = "bfloat16",
    device: Optional[DeviceSpec] = None,
    tp: int = 1,
) -> Dict[str, float]:
    """Lower-bound decode step time / tok/s on one chip (``tp`` > 1 divides
    both streams across chips; ICI cost of the per-layer all-reduce is the
    cost census' job, not this closed form's).

    t_step >= max(weight+KV bytes / HBM bw, matmul+attention FLOPs / peak).
    Decode on every committed shape is HBM-bound; the FLOPs term exists so
    large-batch projections stay honest.
    """
    spec = device or get_device()
    mm = matmul_params(attrs)
    nq = attrs["num_attention_heads"]
    D = attrs.get("head_dim") or attrs["hidden_size"] // nq
    L = attrs["num_hidden_layers"]

    weight_bytes = mm["total"] * _itemsize(weight_dtype)
    kv_read = batch * kv_width * kv_bytes_per_token(attrs, kv_dtype)
    hbm_bytes = (weight_bytes + kv_read) / tp
    # per token: every matmul weight once (2 FLOPs/param) + QK^T and PV at
    # the live kv width (2 + 2 FLOPs per (head, pos, dim) slot)
    flops = batch * (2 * mm["total"] + 4 * L * nq * D * kv_width) / tp

    t_hbm = hbm_bytes / spec.hbm_bw
    t_flops = flops / spec.peak("bfloat16")  # matmuls compute in bf16
    t_step = max(t_hbm, t_flops)
    return {
        "t_step_s": t_step,
        "t_hbm_s": t_hbm,
        "t_flops_s": t_flops,
        "tok_s": batch / t_step,
        "bound": "hbm" if t_hbm >= t_flops else "flops",
        "weight_bytes": int(weight_bytes),
        "kv_read_bytes": int(kv_read),
        "device": spec.name,
    }


def prefill_projection(
    attrs: dict,
    *,
    batch: int,
    seq: int,
    weight_dtype: str = "bfloat16",
    device: Optional[DeviceSpec] = None,
    tp: int = 1,
) -> Dict[str, float]:
    """Lower-bound prefill (context-encoding) pass: matmul FLOPs over S
    tokens + causal attention FLOPs (S²/2), against peak; plus the one
    weight stream against HBM."""
    spec = device or get_device()
    mm = matmul_params(attrs)
    nq = attrs["num_attention_heads"]
    D = attrs.get("head_dim") or attrs["hidden_size"] // nq
    L = attrs["num_hidden_layers"]

    flops = batch * (2 * mm["total"] * seq + 4 * L * nq * D * seq * seq / 2) / tp
    hbm_bytes = mm["total"] * _itemsize(weight_dtype) / tp
    t_flops = flops / spec.peak("bfloat16")
    t_hbm = hbm_bytes / spec.hbm_bw
    t_pass = max(t_flops, t_hbm)
    return {
        "t_pass_s": t_pass,
        "tok_s": batch * seq / t_pass,
        "bound": "flops" if t_flops >= t_hbm else "hbm",
        "flops": int(flops),
        "device": spec.name,
    }


#: the bench spec-serving draft shape: a 1B-width, 4-layer truncation (the
#: EAGLE-class "few-layer draft over the target's width" regime; bench.py's
#: spec-ragged row builds its random-weight draft from the same dict so the
#: projection and the measurement share one shape definition)
LLAMA_1B_DRAFT4 = dict(LLAMA_1B, num_hidden_layers=4)


def expected_accept_tokens(acceptance: float, draft_len: int) -> float:
    """Expected tokens committed per speculation round under greedy
    contiguous-match verification with per-draft acceptance probability
    ``acceptance`` and ``draft_len`` drafted tokens: the leading-match
    length of a geometric chain, 1 + a + a² + … + a^L (PERF.md
    "acceptance-vs-tok/s"). At a = 0.8, L = 3 that is 2.95 tokens/round."""
    a = float(acceptance)
    L = int(draft_len)
    if a >= 1.0:
        return L + 1.0
    return (1.0 - a ** (L + 1)) / (1.0 - a)


def spec_decode_projection(
    attrs: dict,
    *,
    batch: int,
    kv_width: int,
    acceptance: float,
    draft_len: int,
    draft_attrs: Optional[dict] = None,
    weight_dtype: str = "bfloat16",
    kv_dtype: str = "bfloat16",
    device: Optional[DeviceSpec] = None,
    tp: int = 1,
) -> Dict[str, float]:
    """Draft-assisted decode ceiling at a given ACCEPTANCE RATE — the
    acceptance-parameterized projection the spec-serving bench row and
    ``--compare`` consume.

    One round = one packed verify pass over ``draft_len + 1`` query tokens
    per row (HBM cost == a plain decode step: weights stream once, the KV
    read is the same cache walk; FLOPs scale by the extra query tokens —
    still far under the ridge at serving widths) + ``draft_len`` sequential
    draft decode steps on ``draft_attrs`` (default :data:`LLAMA_1B_DRAFT4`).
    Expected committed tokens/round follow the geometric acceptance chain
    (:func:`expected_accept_tokens`), so::

        tok_s = batch * E[tokens/round] / (t_verify + draft_len * t_draft)

    At acceptance 1.0 with a free draft this recovers (draft_len+1)× the
    plain decode ceiling; at acceptance 0 it degrades to plain decode taxed
    by the draft — the model PERF r5's ">500 tok/s at int8+EAGLE
    (acceptance 0.8)" figure comes from."""
    spec = device or get_device()
    verify = decode_projection(
        attrs, batch=batch, kv_width=kv_width, weight_dtype=weight_dtype,
        kv_dtype=kv_dtype, device=spec, tp=tp,
    )
    # the verify pass computes draft_len+1 query positions per row: same
    # HBM traffic, (draft_len+1)x the matmul/attention FLOPs
    t_verify = max(verify["t_hbm_s"], verify["t_flops_s"] * (draft_len + 1))
    d_attrs = draft_attrs if draft_attrs is not None else LLAMA_1B_DRAFT4
    draft_step = decode_projection(
        d_attrs, batch=batch, kv_width=kv_width, weight_dtype=weight_dtype,
        kv_dtype=kv_dtype, device=spec, tp=tp,
    )
    t_round = t_verify + draft_len * draft_step["t_step_s"]
    e_tokens = expected_accept_tokens(acceptance, draft_len)
    return {
        "t_round_s": t_round,
        "t_verify_s": t_verify,
        "t_draft_s": draft_len * draft_step["t_step_s"],
        "expected_tokens_per_round": e_tokens,
        "acceptance": float(acceptance),
        "draft_len": int(draft_len),
        "tok_s": batch * e_tokens / t_round,
        "bound": verify["bound"],
        "weight_bytes": verify["weight_bytes"],
        "kv_read_bytes": verify["kv_read_bytes"],
        "device": spec.name,
    }


# ---------------------------------------------------------------------------
# bench-row projection table (the non-tiny bench.py suite shapes)
# ---------------------------------------------------------------------------

#: each measured bench row's analytic shape — kv_width is the TKG bucket the
#: measured decode actually runs at (bench._suite_params non-tiny values);
#: kind "serving" projects the aggregate device ceiling at the slot count.
BENCH_ROW_MODELS: Dict[str, dict] = {
    "bf16_1b_bs1": dict(model=LLAMA_1B, kind="decode", batch=1, kv_width=512,
                        weight_dtype="bfloat16", kv_dtype="bfloat16"),
    "bf16_1b_bs4": dict(model=LLAMA_1B, kind="decode", batch=4, kv_width=512,
                        weight_dtype="bfloat16", kv_dtype="bfloat16"),
    "int8_1b_bs1": dict(model=LLAMA_1B, kind="decode", batch=1, kv_width=512,
                        weight_dtype="int8", kv_dtype="bfloat16"),
    "serving_1b_int8": dict(model=LLAMA_1B, kind="serving", batch=8,
                            kv_width=1024, weight_dtype="int8",
                            kv_dtype="bfloat16"),
    "serving_1b_int8_ragged": dict(model=LLAMA_1B, kind="serving", batch=8,
                                   kv_width=1024, weight_dtype="int8",
                                   kv_dtype="bfloat16"),
    "serving_1b_int8_ragged_async": dict(model=LLAMA_1B, kind="serving",
                                         batch=8, kv_width=1024,
                                         weight_dtype="int8",
                                         kv_dtype="bfloat16"),
    # spec-serving row (serving_spec_ragged): the acceptance-parameterized
    # projection — PERF r5's committed operating point is acceptance 0.8
    # with a k=4 program (3 drafts); bench.py records the MEASURED
    # acceptance beside it (spec_ragged_acceptance) so hardware session
    # zero can re-project at the observed rate before judging the error
    "serving_1b_int8_spec_ragged": dict(model=LLAMA_1B, kind="serving_spec",
                                        batch=8, kv_width=1024,
                                        weight_dtype="int8",
                                        kv_dtype="bfloat16",
                                        acceptance=0.8, draft_len=3,
                                        draft=LLAMA_1B_DRAFT4),
    # router row, as committed: 2 replicas SHARING one chip, 8-request mix
    # -> each replica streams its own weight copy for its 4-request share,
    # so the aggregate ceiling is the batch-4 single-chip projection (NOT
    # batch-8: two weight streams halve the per-replica bandwidth). On
    # scale-out hardware bench.py multiplies by the count of
    # non-overlapping replica meshes instead.
    "serving_1b_int8_router": dict(model=LLAMA_1B, kind="serving", batch=4,
                                   kv_width=1024, weight_dtype="int8",
                                   kv_dtype="bfloat16"),
    # threaded-stepping row (router_threading): the DEVICE ceiling is the
    # same as the sequential router row — threading removes host
    # serialization, it does not change what each replica's chip streams;
    # the row's win shows up as measured tok/s approaching this same
    # projection (and in router_step_overlap_frac), not as a new ceiling
    "serving_1b_int8_router_threaded": dict(
        model=LLAMA_1B, kind="serving", batch=4, kv_width=1024,
        weight_dtype="int8", kv_dtype="bfloat16"),
    # disaggregated-prefill-tier row (ISSUE 15): the DEVICE ceiling is the
    # router row's — the tier moves WHERE prefill runs (a dedicated
    # replica), not what each decode chip streams per request; the row's
    # own numbers (handoffs, hand-off failure census, local-prefill
    # fallbacks) are containment metrics the device model does not project
    "serving_1b_int8_disagg": dict(model=LLAMA_1B, kind="serving", batch=4,
                                   kv_width=1024, weight_dtype="int8",
                                   kv_dtype="bfloat16"),
    # elastic add/retire row (ISSUE 20): the DEVICE ceiling is the router
    # row's — retiring one replica mid-drain and adding a fresh one changes
    # WHICH replica streams each request, not what a replica's chip streams
    # per step; the row's own numbers (retired/added counts, leaked blocks
    # and threads, attainment vs the static drain) are stewardship metrics
    # the device model does not project
    "serving_1b_int8_elastic": dict(model=LLAMA_1B, kind="serving", batch=4,
                                    kv_width=1024, weight_dtype="int8",
                                    kv_dtype="bfloat16"),
    # open-loop goodput rows (ISSUE 14): the DEVICE ceiling is the same
    # full-slot serving projection — goodput (SLO-met tokens/s) is bounded
    # by throughput, which is bounded by this; the rows' own numbers
    # (attainment, dip, recovery) are workload metrics the device model
    # does not project. The chaos row's 2 replicas share the committed
    # 1-chip harness, so its ceiling stays the single-mesh projection.
    "serving_1b_int8_goodput": dict(model=LLAMA_1B, kind="serving", batch=8,
                                    kv_width=1024, weight_dtype="int8",
                                    kv_dtype="bfloat16"),
    "serving_1b_int8_goodput_burst": dict(model=LLAMA_1B, kind="serving",
                                          batch=8, kv_width=1024,
                                          weight_dtype="int8",
                                          kv_dtype="bfloat16"),
    "serving_1b_int8_goodput_chaos": dict(model=LLAMA_1B, kind="serving",
                                          batch=8, kv_width=1024,
                                          weight_dtype="int8",
                                          kv_dtype="bfloat16"),
    # disaggregated chaos row (ISSUE 15): same full-slot serving ceiling —
    # the prefill-tier kill is a containment scenario (decode capacity
    # survives; placements degrade to local prefill), not a new ceiling
    "serving_1b_int8_disagg_chaos": dict(model=LLAMA_1B, kind="serving",
                                         batch=8, kv_width=1024,
                                         weight_dtype="int8",
                                         kv_dtype="bfloat16"),
    "int8_8b_bs1": dict(model=LLAMA_8B, kind="decode", batch=1, kv_width=512,
                        weight_dtype="int8", kv_dtype="bfloat16"),
    # w4 rows (ISSUE 17): grouped-int4 packed weights (ops/quant_matmul).
    # The 8B decode row is the flagship — weight-read bytes drop ~2x vs the
    # int8 row above, and the projection's ceiling moves with them.
    "bf16_8b_int4": dict(model=LLAMA_8B, kind="decode", batch=1, kv_width=512,
                         weight_dtype="int4", kv_dtype="bfloat16"),
    "serving_1b_int4_ragged": dict(model=LLAMA_1B, kind="serving", batch=8,
                                   kv_width=1024, weight_dtype="int4",
                                   kv_dtype="bfloat16"),
    "bf16_1b_8k": dict(model=LLAMA_1B, kind="decode", batch=1, kv_width=8704,
                       weight_dtype="bfloat16", kv_dtype="bfloat16"),
    "bf16_1b_8k_kvq8": dict(model=LLAMA_1B, kind="decode", batch=1,
                            kv_width=8704, weight_dtype="bfloat16",
                            kv_dtype="int8"),
    "bf16_1b_16k": dict(model=LLAMA_1B, kind="decode", batch=1,
                        kv_width=16896, weight_dtype="bfloat16",
                        kv_dtype="bfloat16"),
    "bf16_1b_16k_kvq8": dict(model=LLAMA_1B, kind="decode", batch=1,
                             kv_width=16896, weight_dtype="bfloat16",
                             kv_dtype="int8"),
}


def project_bench_row(name: str, device: Optional[DeviceSpec] = None) -> Optional[dict]:
    """Projected decode tok/s (device ceiling) for one bench row name; None
    for rows the table doesn't model. ``serving_spec`` rows project through
    the acceptance-parameterized speculative model."""
    row = BENCH_ROW_MODELS.get(name)
    if row is None:
        return None
    if row.get("kind") == "serving_spec":
        return spec_decode_projection(
            row["model"], batch=row["batch"], kv_width=row["kv_width"],
            acceptance=row["acceptance"], draft_len=row["draft_len"],
            draft_attrs=row.get("draft"),
            weight_dtype=row["weight_dtype"], kv_dtype=row["kv_dtype"],
            device=device,
        )
    return decode_projection(
        row["model"], batch=row["batch"], kv_width=row["kv_width"],
        weight_dtype=row["weight_dtype"], kv_dtype=row["kv_dtype"],
        device=device,
    )


#: bench summary-line key -> (row whose projection it compares against,
#: summary key holding the run's OWN recorded projection or None). A
#: recorded projection wins over the static table: the run knows things
#: the table cannot (e.g. the router row's count of non-overlapping
#: replica meshes on multi-chip hardware), so the bench row and the
#: --compare report can never disagree about the same run.
COMPARE_KEYS = (
    ("value", "bf16_1b_bs1", "projected_tok_s"),
    ("decode_bs4_tok_s", "bf16_1b_bs4", None),
    ("int8_1b_tok_s", "int8_1b_bs1", None),
    ("serving_tok_s", "serving_1b_int8", "serving_projected_tok_s"),
    ("ragged_tok_s", "serving_1b_int8_ragged", None),
    ("ragged_async_tok_s", "serving_1b_int8_ragged_async", None),
    # the spec row records its own projection: the bench re-projects at the
    # MEASURED acceptance rate, which the static table cannot know
    ("spec_ragged_tok_s", "serving_1b_int8_spec_ragged",
     "spec_ragged_projected_tok_s"),
    ("router_tok_s", "serving_1b_int8_router", "router_projected_tok_s"),
    ("router_threaded_tok_s", "serving_1b_int8_router_threaded", None),
    # goodput vs the same serving ceiling: the gap between goodput_tok_s
    # and the projection decomposes into (device gap) x (SLO attainment) —
    # the report line makes an SLO-driven collapse visible offline
    ("goodput_tok_s", "serving_1b_int8_goodput", None),
    ("int8_8b_tok_s", "int8_8b_bs1", None),
    # w4 rows record their own projections (the run re-derives them at the
    # measured shape), so the static table is the fallback comparator
    ("w4_tok_s", "bf16_8b_int4", "w4_projected_tok_s"),
    ("w4_serving_tok_s", "serving_1b_int4_ragged", "w4_serving_projected_tok_s"),
    ("ctx8k_tok_s", "bf16_1b_8k", None),
    ("kvq8_8k_tok_s", "bf16_1b_8k_kvq8", None),
    ("long_ctx_tok_s", "bf16_1b_16k", None),
    ("kvq8_16k_tok_s", "bf16_1b_16k_kvq8", None),
)


def compare_report(path: str) -> str:
    """Offline measured-vs-projected report over a committed bench summary
    (``BENCH_rNN.json`` — either the raw summary line or the driver wrapper
    with the summary under ``"parsed"``). Informational: per-row error
    fractions, no gate — hardware session zero's comparison tool."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(
            f"bench summary must be a JSON object, got {type(data).__name__}"
        )
    if isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    device_str = str(data.get("device") or "")
    spec = resolve_device(device_str)
    resolved = spec is not None
    spec = spec or get_device()
    note = "" if resolved else (
        f", UNRESOLVED: projecting {DEFAULT_DEVICE} — errors are not meaningful"
    )
    lines = [
        f"measured-vs-projected (device {device_str or '<none>'} -> "
        f"{spec.name} spec{note})",
        f"  {'row':<30} {'measured':>10} {'projected':>10} {'err':>8}  bound",
    ]
    n = 0
    for key, row_name, recorded_key in COMPARE_KEYS:
        measured = data.get(key)
        if measured is None:
            continue
        proj = project_bench_row(row_name, spec)
        if proj is None:
            continue
        recorded = data.get(recorded_key) if recorded_key else None
        projected = recorded if recorded else proj["tok_s"]
        err = measured / projected - 1.0
        lines.append(
            f"  {row_name:<30} {measured:>10.1f} {projected:>10.1f} "
            f"{err:>+7.1%}  {proj['bound']}"
            f"{' (recorded)' if recorded else ''}"
        )
        n += 1
    if n == 0:
        lines.append("  (no comparable tok/s keys found in the summary)")
    lines.append(
        "projections are nameplate lower bounds on time: measured/projected"
        " - 1 near 0 means device-limited; strongly negative means host/"
        "relay gap or model error — see PERF.md 'Static roofline cost model'"
    )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# PERF.md table renderer
# ---------------------------------------------------------------------------


def _fmt_bytes(n: float) -> str:
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("KB", 1e3)):
        if n >= div:
            return f"{n / div:.1f} {unit}"
    return f"{n:.0f} B"


def render_projection_tables(device: str = DEFAULT_DEVICE) -> str:
    """The markdown tables PERF.md commits (regenerate with
    ``python -m neuronx_distributed_inference_tpu.analysis.device_model``)."""
    spec = get_device(device)
    out = [
        f"<!-- generated by python -m neuronx_distributed_inference_tpu."
        f"analysis.device_model ({spec.name}) — edit the model, not the "
        f"table -->",
        "",
        f"Device: {spec.name} — bf16 peak "
        f"{spec.peak_flops['bfloat16'] / 1e12:.0f} TFLOP/s, int8 "
        f"{spec.peak_flops['int8'] / 1e12:.0f}, HBM "
        f"{spec.hbm_bw / 1e9:.0f} GB/s, ICI {spec.ici_bw / 1e9:.0f} GB/s, "
        f"VMEM {spec.vmem_bytes // (1024 ** 2)} MiB/core, "
        f"ridge {spec.ridge_flops_per_byte:.0f} FLOP/byte.",
        "",
        "| bench row | weights | KV read/step | bound | projected tok/s |",
        "|---|---|---|---|---|",
    ]
    for name, row in BENCH_ROW_MODELS.items():
        p = project_bench_row(name, spec)
        out.append(
            f"| {name} (bs={row['batch']}, kv {row['kv_width']}) | "
            f"{_fmt_bytes(p['weight_bytes'])} | "
            f"{_fmt_bytes(p['kv_read_bytes'])} | {p['bound']} | "
            f"{p['tok_s']:.0f} |"
        )
    out += [
        "",
        "| prefill | prompt | lower-bound wall | prefill tok/s ceiling |",
        "|---|---|---|---|",
    ]
    for name, attrs, seq in (
        ("1B bf16", LLAMA_1B, 512),
        ("1B bf16", LLAMA_1B, 2048),
        ("1B bf16", LLAMA_1B, 8192),
        ("1B bf16", LLAMA_1B, 16384),
        ("8B int8", LLAMA_8B, 512),
    ):
        p = prefill_projection(attrs, batch=1, seq=seq, device=spec)
        out.append(
            f"| {name} | {seq} | {p['t_pass_s'] * 1e3:.0f} ms | "
            f"{p['tok_s'] / 1e3:.1f}k |"
        )
    return "\n".join(out)


if __name__ == "__main__":  # pragma: no cover - exercised via PERF.md regen
    print(render_projection_tables())
