"""Shared program harness for the graph / shard / memory audit suites.

Every HLO-level analyzer needs the same expensive artifact: the committed
(phase, bucket) programs of a tiny tp-sharded model, traced/lowered/compiled
on the 8-virtual-device CPU mesh (the same GSPMD path hardware takes). This
module builds them ONCE per process and hands each suite a
:class:`ProgramRecord` carrying every view the rules consume:

- the jaxpr (bucket-skeleton / dtype rules),
- the donation-annotated StableHLO text (donation attrs),
- the partitioned executable (collective census, realized shardings,
  ``input_output_alias`` table, memory analysis),
- the DECLARED sharding contract (builder/mesh PartitionSpec trees via
  ``TpuModelForCausalLM.declared_pspecs()``), and
- the flat HLO parameter-number range of the donated cache leaves (what the
  alias table is checked against).

Program families:

- the committed tags the graph audit covers —
  ``context_encoding`` / ``token_generation`` / ``fused_speculation``, the
  ``*_kvq8`` quantized-cache variants (contiguous cache; the
  ``fused_speculation_kvq8`` variant quantizes BOTH the draft and target
  caches — the spec-decode path the cost model covers), and ``mixed_step``
  (the ragged mixed prefill+decode serving program on the int8 paged
  cache, bucketed by TOTAL packed query tokens), and
- two cache-VARIANT decode programs for the memory audit's donation proof:
  ``token_generation_ring`` (ring-bounded sliding-window cache) and
  ``token_generation_paged`` (paged block cache), both compiled with
  ``kv_cache_dtype="int8"`` so the QuantizedKV code+scale leaves are audited
  in every variant.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

TAG_CONTEXT_ENCODING = "context_encoding"
TAG_TOKEN_GENERATION = "token_generation"
TAG_FUSED_SPECULATION = "fused_speculation"
TAG_CONTEXT_ENCODING_KVQ8 = "context_encoding_kvq8"
TAG_TOKEN_GENERATION_KVQ8 = "token_generation_kvq8"
# fused-speculation TKG on the int8 contiguous cache (draft AND target
# quantized): the spec-decode path ROADMAP item 2 optimizes — committed so
# the graph/shard/memory/cost audits cover it like the plain kvq8 pair
TAG_FUSED_SPECULATION_KVQ8 = "fused_speculation_kvq8"
TAG_TOKEN_GENERATION_RING = "token_generation_ring"
TAG_TOKEN_GENERATION_PAGED = "token_generation_paged"
# ragged mixed prefill+decode serving step (serving_ragged): int8 PAGED
# cache, bucket axis = total packed query tokens (runtime/model_runner.py
# MixedStepRunner) — committed so the graph/shard/memory audits cover the
# one-dispatch serving program family from day one
TAG_MIXED_STEP = "mixed_step"
# the SPEC-VERIFY variant of the mixed family (serving_spec_ragged,
# spec_width = speculation_length): spec rows pack draft tokens as extra
# query positions, the program gathers per-row verify windows and computes
# the greedy acceptance count on device — committed so the GRAPH/SHARD/MEM/
# COST audits see the speculative serving program the same day it ships
TAG_MIXED_STEP_SPEC = "mixed_step_spec"
# the w4 family (weight_dtype="int4", ISSUE 17): decode programs whose
# weights are packed grouped-int4 (uint8 codes + f32 group scales,
# ops/quant_matmul) — committed so the graph/shard/memory audits cover the
# packed-weight leaves and the cost audit (COST501) accounts decode
# weight-read bytes at 0.5 byte/param (~0.25x the bf16 stream)
TAG_TOKEN_GENERATION_W4 = "token_generation_w4"
TAG_MIXED_STEP_W4 = "mixed_step_w4"

#: the committed program set (graph + shard audits)
COMMITTED_TAGS = (
    TAG_CONTEXT_ENCODING,
    TAG_TOKEN_GENERATION,
    TAG_FUSED_SPECULATION,
    TAG_CONTEXT_ENCODING_KVQ8,
    TAG_TOKEN_GENERATION_KVQ8,
    TAG_FUSED_SPECULATION_KVQ8,
    TAG_MIXED_STEP,
    TAG_MIXED_STEP_SPEC,
    TAG_TOKEN_GENERATION_W4,
    TAG_MIXED_STEP_W4,
)
#: cache-variant decode programs (memory audit: donation across variants)
CACHE_VARIANT_TAGS = (
    TAG_TOKEN_GENERATION_RING,
    TAG_TOKEN_GENERATION_PAGED,
)
ALL_TAGS = COMMITTED_TAGS + CACHE_VARIANT_TAGS

COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

PHASE_CTE = "cte"
PHASE_TKG = "tkg"


def path_str(path) -> str:
    """Canonical "/"-joined string for a pytree key path — the ONE leaf-path
    format shared by the shard-audit census keys and the memory-audit
    finding names (e.g. ``layers/mlp/gate_proj/weight``, ``k/scale``)."""
    parts = []
    for p in path:
        for attr in ("key", "name", "idx"):
            v = getattr(p, attr, None)
            if v is not None:
                parts.append(str(v))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def census(hlo_text: str) -> Dict[str, int]:
    """Collective census of a compiled HLO module (result definitions, so
    fused start/done pairs count once)."""
    counts = {}
    for op in COLLECTIVE_OPS:
        counts[op] = len(
            re.findall(r"%?" + op + r"(?:-start)?(?:\.\d+)? = ", hlo_text)
        )
    return counts


def donation_count(lowered_text: str) -> int:
    """Donation/alias attrs that survived to the StableHLO lowering."""
    return lowered_text.count("tf.aliasing_output") + lowered_text.count(
        "jax.buffer_donor"
    )


@dataclass(frozen=True)
class ShapeMeta:
    """FLOP-relevant shape metadata of one (tag, bucket) program — recorded
    at build time, where the config is in hand, so the cost audit
    (:mod:`.cost_audit`) can turn graph-derived FLOP counts into an HBM
    traffic model without re-deriving the cache layout:

    - ``rows``: batch rows the step serves (serving slots for mixed_step);
    - ``q_tokens``: query tokens processed per dispatch (CTE: B·S, TKG: B,
      fused: B·(spec_len+1) verify positions, mixed: the packed bucket);
    - ``kv_width``: cache positions attention READS per row this bucket
      (0 for CTE — prefill K/V are activations, not cache reads);
    - ``cache_capacity_tokens``: total token slots of the cache pool (per
      cache stream), so per-token cache bytes = leaf bytes / capacity;
    - ``q_tile``/``spec_len``: the mixed-step packing granule and the
      fused-speculation draft length (COST503's packing contract).
    """

    rows: int
    q_tokens: int
    kv_width: int
    cache_capacity_tokens: int
    hidden: int
    layers: int
    vocab: int
    q_tile: int = 0
    spec_len: int = 0


@dataclass
class ProgramRecord:
    """One committed (tag, bucket) program plus its audit views."""

    tag: str
    phase: str
    bucket: int
    jaxpr: object  # ClosedJaxpr of the traced step
    lowered_text: str  # StableHLO with donation attrs
    compiled: object  # jax Compiled (partitioned executable)
    census: Dict[str, int]
    donation_count: int
    params: object  # committed param tree (tiny arrays)
    cache: object  # committed cache tree
    declared_param_pspecs: object
    declared_cache_pspecs: object
    realized_param_shardings: object  # pytree of NamedSharding, params slot
    realized_cache_shardings: object  # pytree of NamedSharding, cache slot
    output_cache_shardings: Optional[object]  # realized cache OUTPUT shardings
    mesh: object
    n_param_leaves: int
    cache_param_range: Tuple[int, int]  # flat HLO param numbers of cache leaves
    shape_meta: Optional[ShapeMeta] = None  # cost-audit metadata
    _compiled_text: Optional[str] = field(default=None, repr=False)

    @property
    def n_cache_leaves(self) -> int:
        return self.cache_param_range[1] - self.cache_param_range[0]

    @property
    def compiled_text(self) -> str:
        if self._compiled_text is None:
            self._compiled_text = self.compiled.as_text()
        return self._compiled_text


# ---------------------------------------------------------------------------
# tiny audit model
# ---------------------------------------------------------------------------


def _tiny_hf_attrs(vocab: int = 128) -> dict:
    return dict(
        model_type="llama",
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=2,
        vocab_size=vocab,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        max_position_embeddings=256,
        hidden_act="silu",
        tie_word_embeddings=False,
    )


def tiny_config(hf_attrs: Optional[dict] = None, **tpu_overrides):
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig

    attrs = _tiny_hf_attrs()
    if hf_attrs:
        attrs.update(hf_attrs)

    def load_config(cfg):
        for k, v in attrs.items():
            setattr(cfg, k, v)

    tc_kwargs = dict(
        batch_size=2,
        seq_len=128,
        dtype="bfloat16",
        tp_degree=2,
        context_encoding_buckets=[64, 128],
        token_generation_buckets=[64, 128],
    )
    tc_kwargs.update(tpu_overrides)
    return LlamaInferenceConfig(TpuConfig(**tc_kwargs), load_config=load_config)


# ---------------------------------------------------------------------------
# record assembly
# ---------------------------------------------------------------------------


def _input_shardings(compiled):
    """The compiled executable's realized per-argument shardings (a tuple of
    pytrees matching the step function's positional args)."""
    ish = compiled.input_shardings
    # jax returns (arg_shardings, kwarg_shardings)
    return ish[0] if isinstance(ish, tuple) and len(ish) == 2 else ish


def _output_cache_shardings(compiled, attr: str = "cache"):
    """Realized sharding subtree of the step OUTPUT's cache field (None when
    the output structure doesn't expose one — audits degrade gracefully)."""
    try:
        out = compiled.output_shardings
        return getattr(out, attr, None)
    except Exception:
        return None


def _cache_capacity(cache, paged: bool) -> int:
    """Total token slots of a cache pool: rows × positions for the
    contiguous/ring layout (L, rows, S, H, D), blocks × block_size for the
    paged layout (L, blocks, H, block_size, D)."""
    import jax

    for leaf in jax.tree.leaves(cache):
        if getattr(leaf, "ndim", 0) >= 4:
            return int(leaf.shape[1] * (leaf.shape[3] if paged else leaf.shape[2]))
    return 0


def _record_from_runner(
    tag: str,
    phase: str,
    runner,
    app,
    bucket: int,
    declared_pp,
    declared_cp,
    shape_meta: Optional[ShapeMeta] = None,
) -> ProgramRecord:
    import jax

    inputs = runner.example_inputs(bucket)
    traced, lowered, compiled = runner.trace_program(
        app.params, app.kv_cache, inputs, None
    )
    lowered_text = lowered.as_text()
    compiled_text = compiled.as_text()
    n_p = len(jax.tree.leaves(app.params))
    n_c = len(jax.tree.leaves(app.kv_cache))
    ish = _input_shardings(compiled)
    return ProgramRecord(
        tag=tag,
        phase=phase,
        bucket=bucket,
        jaxpr=traced.jaxpr,
        lowered_text=lowered_text,
        compiled=compiled,
        census=census(compiled_text),
        donation_count=donation_count(lowered_text),
        params=app.params,
        cache=app.kv_cache,
        declared_param_pspecs=declared_pp,
        declared_cache_pspecs=declared_cp,
        realized_param_shardings=ish[0],
        realized_cache_shardings=ish[1],
        output_cache_shardings=_output_cache_shardings(compiled),
        mesh=app.mesh,
        n_param_leaves=n_p,
        cache_param_range=(n_p, n_p + n_c),
        shape_meta=shape_meta,
        _compiled_text=compiled_text,
    )


def _build_causal(
    kv_quant: bool = False,
    variant: Optional[str] = None,
    weight_dtype: Optional[str] = None,
) -> Dict[str, Dict[int, ProgramRecord]]:
    """CTE + TKG programs of the tiny causal LM.

    ``kv_quant``: contiguous cache with kv_cache_dtype="int8" (the kvq8 tag
    pair). ``variant``: "ring" (sliding-window ring-bounded cache), "paged"
    (block cache) or "mixed" (the ragged mixed-step serving program on the
    paged cache, serving_ragged) — compiled int8 so the QuantizedKV
    code+scale leaves are covered in every cache variant.
    ``weight_dtype="int4"``: the w4 family — packed grouped-int4 weights
    (ops/quant_matmul) through the plain TKG and mixed-step programs.
    """
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    overrides = {}
    if kv_quant or variant:
        overrides["kv_cache_dtype"] = "int8"
    if weight_dtype:
        overrides["weight_dtype"] = weight_dtype
    if variant == "ring":
        overrides["sliding_window"] = 32
    elif variant == "paged":
        overrides.update(
            is_block_kv_layout=True, pa_block_size=16, pa_num_blocks=18
        )
    elif variant in ("mixed", "mixed_spec"):
        from neuronx_distributed_inference_tpu.config import ChunkedPrefillConfig

        overrides.update(
            is_block_kv_layout=True,
            pa_block_size=16,
            pa_num_blocks=24,
            is_continuous_batching=True,
            is_chunked_prefill=True,
            chunked_prefill_config=ChunkedPrefillConfig(
                max_num_seqs=2, kernel_q_tile_size=16
            ),
            serving_ragged=True,
        )
        if variant == "mixed_spec":
            overrides.update(
                serving_spec_ragged=True, speculation_length=_SPEC_WIDTH
            )
    hf_attrs = None
    if weight_dtype == "int4":
        # w4 runs the kernel-eligible tiny shape: every decode linear has
        # K ≥ one double-group (256) so packing isn't padding-dominated and
        # the COST501 census shows the real weight-byte halving, and
        # head_dim 64 is lane-aligned so mixed_step_w4 satisfies the
        # ragged-dispatch gate the sharded kernel serves on hardware
        hf_attrs = dict(hidden_size=256, intermediate_size=512)
    cfg = tiny_config(hf_attrs=hf_attrs, **overrides)
    app = TpuModelForCausalLM(None, cfg)
    app.load(random_weights=True)
    declared_pp, declared_cp = app.declared_pspecs()

    if variant == "ring":
        pairs = [(TAG_TOKEN_GENERATION_RING, PHASE_TKG, app.token_generation_model)]
    elif variant == "paged":
        pairs = [(TAG_TOKEN_GENERATION_PAGED, PHASE_TKG, app.token_generation_model)]
    elif variant == "mixed" and weight_dtype == "int4":
        pairs = [(TAG_MIXED_STEP_W4, PHASE_TKG, app.mixed_step_model)]
    elif weight_dtype == "int4":
        pairs = [(TAG_TOKEN_GENERATION_W4, PHASE_TKG, app.token_generation_model)]
    elif variant == "mixed":
        pairs = [(TAG_MIXED_STEP, PHASE_TKG, app.mixed_step_model)]
    elif variant == "mixed_spec":
        pairs = [(TAG_MIXED_STEP_SPEC, PHASE_TKG, app.mixed_step_model)]
    elif kv_quant:
        pairs = [
            (TAG_CONTEXT_ENCODING_KVQ8, PHASE_CTE, app.context_encoding_model),
            (TAG_TOKEN_GENERATION_KVQ8, PHASE_TKG, app.token_generation_model),
        ]
    else:
        pairs = [
            (TAG_CONTEXT_ENCODING, PHASE_CTE, app.context_encoding_model),
            (TAG_TOKEN_GENERATION, PHASE_TKG, app.token_generation_model),
        ]
    window = overrides.get("sliding_window", 0)
    capacity = _cache_capacity(
        app.kv_cache, paged=variant in ("paged", "mixed", "mixed_spec")
    )
    B = cfg.tpu_config.batch_size

    def meta(tag, phase, runner, bucket) -> ShapeMeta:
        base = dict(
            cache_capacity_tokens=capacity,
            hidden=cfg.hidden_size,
            layers=cfg.num_hidden_layers,
            vocab=cfg.vocab_size,
        )
        if tag in (TAG_MIXED_STEP, TAG_MIXED_STEP_SPEC, TAG_MIXED_STEP_W4):
            # packed bucket = query tokens; decode rows read the widest
            # committed kv bucket (the width example_inputs compiles at);
            # the spec variant records its draft length (spec_width - 1) so
            # the cost audit's tok_s upper bound counts the up-to-spec_width
            # tokens a fully-accepted verify row commits
            return ShapeMeta(
                rows=runner.num_rows, q_tokens=bucket,
                kv_width=runner.kv_buckets[-1], q_tile=runner.q_tile,
                spec_len=getattr(runner, "spec_width", 1) - 1, **base
            )
        if phase == PHASE_CTE:
            return ShapeMeta(rows=B, q_tokens=B * bucket, kv_width=0, **base)
        return ShapeMeta(
            rows=B, q_tokens=B,
            kv_width=min(bucket, window) if window else bucket, **base
        )

    out: Dict[str, Dict[int, ProgramRecord]] = {}
    for tag, phase, runner in pairs:
        out[tag] = {
            bucket: _record_from_runner(
                tag, phase, runner, app, bucket, declared_pp, declared_cp,
                shape_meta=meta(tag, phase, runner, bucket),
            )
            for bucket in runner.buckets
        }
    return out


def _build_fused(kv_quant: bool = False) -> Dict[str, Dict[int, ProgramRecord]]:
    """The fused-speculation decode program across ≥2 TKG bucket widths
    (draft chain + target verify in ONE graph). Params/caches/specs are
    keyed ``{"draft": ..., "target": ...}`` in the program's arg order.
    ``kv_quant``: both caches on kv_cache_dtype="int8" (the spec-decode
    path the cost model must cover — ROADMAP item 2)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuronx_distributed_inference_tpu.config import (
        FusedSpecConfig,
        OnDeviceSamplingConfig,
    )
    from neuronx_distributed_inference_tpu.models.base import StepInputs
    from neuronx_distributed_inference_tpu.modules.sampling import (
        prepare_sampling_params,
    )
    from neuronx_distributed_inference_tpu.runtime.fused_spec import (
        TpuFusedSpecModelForCausalLM,
    )

    spec_len = 3
    overrides = {"kv_cache_dtype": "int8"} if kv_quant else {}
    cfg = tiny_config(
        speculation_length=spec_len,
        enable_fused_speculation=True,
        on_device_sampling_config=OnDeviceSamplingConfig(do_sample=False),
        **overrides,
    )
    cfg.fused_spec_config = FusedSpecConfig(
        draft_model_name="tiny-draft", draft_config=tiny_config(**overrides)
    )
    tag = TAG_FUSED_SPECULATION_KVQ8 if kv_quant else TAG_FUSED_SPECULATION
    app = TpuFusedSpecModelForCausalLM(None, cfg)
    app.load(random_weights=True)
    declared_pp, declared_cp = app.declared_pspecs()

    B = cfg.tpu_config.batch_size
    sp = prepare_sampling_params(B)
    params = {"draft": app.draft_params, "target": app.target_params}
    cache = {"draft": app.draft_cache, "target": app.target_cache}
    n_p = len(jax.tree.leaves(params))
    n_c = len(jax.tree.leaves(cache))
    capacity = _cache_capacity(app.target_cache, paged=False)
    per_bucket: Dict[int, ProgramRecord] = {}
    for bucket in app.tkg_buckets:
        inputs = StepInputs(
            input_ids=jnp.zeros((B, 1), jnp.int32),
            attention_mask=jnp.zeros((B, bucket), jnp.int32),
            position_ids=jnp.full((B, 1), 7, jnp.int32),
            seq_ids=jnp.asarray(np.arange(B, dtype=np.int32)),
            sampling_params=jnp.asarray(sp, jnp.float32),
        )
        traced, lowered, compiled = app.trace_tkg_program(inputs, None)
        lowered_text = lowered.as_text()
        compiled_text = compiled.as_text()
        ish = _input_shardings(compiled)
        per_bucket[bucket] = ProgramRecord(
            tag=tag,
            phase=PHASE_TKG,
            bucket=bucket,
            jaxpr=traced.jaxpr,
            lowered_text=lowered_text,
            compiled=compiled,
            census=census(compiled_text),
            donation_count=donation_count(lowered_text),
            params=params,
            cache=cache,
            declared_param_pspecs=declared_pp,
            declared_cache_pspecs=declared_cp,
            realized_param_shardings={"draft": ish[0], "target": ish[1]},
            realized_cache_shardings={"draft": ish[2], "target": ish[3]},
            output_cache_shardings=None,
            mesh=app.mesh,
            n_param_leaves=n_p,
            cache_param_range=(n_p, n_p + n_c),
            shape_meta=ShapeMeta(
                rows=B,
                q_tokens=B * (spec_len + 1),
                kv_width=bucket,
                cache_capacity_tokens=capacity,
                hidden=cfg.hidden_size,
                layers=cfg.num_hidden_layers,
                vocab=cfg.vocab_size,
                spec_len=spec_len,
            ),
            _compiled_text=compiled_text,
        )
    return {tag: per_bucket}


# ---------------------------------------------------------------------------
# memoized collection
# ---------------------------------------------------------------------------

_MEMO: Dict[str, Dict[int, ProgramRecord]] = {}

#: spec width of the committed mixed_step_spec program (speculation_length)
_SPEC_WIDTH = 4

_BUILDERS = (
    # (tags produced together, builder thunk)
    ((TAG_CONTEXT_ENCODING, TAG_TOKEN_GENERATION), lambda: _build_causal()),
    (
        (TAG_CONTEXT_ENCODING_KVQ8, TAG_TOKEN_GENERATION_KVQ8),
        lambda: _build_causal(kv_quant=True),
    ),
    ((TAG_FUSED_SPECULATION,), _build_fused),
    ((TAG_FUSED_SPECULATION_KVQ8,), lambda: _build_fused(kv_quant=True)),
    ((TAG_MIXED_STEP,), lambda: _build_causal(variant="mixed")),
    ((TAG_MIXED_STEP_SPEC,), lambda: _build_causal(variant="mixed_spec")),
    ((TAG_TOKEN_GENERATION_W4,), lambda: _build_causal(weight_dtype="int4")),
    (
        (TAG_MIXED_STEP_W4,),
        lambda: _build_causal(variant="mixed", weight_dtype="int4"),
    ),
    ((TAG_TOKEN_GENERATION_RING,), lambda: _build_causal(variant="ring")),
    ((TAG_TOKEN_GENERATION_PAGED,), lambda: _build_causal(variant="paged")),
)


def collect_programs(
    tags: Tuple[str, ...] = COMMITTED_TAGS,
) -> Dict[str, Dict[int, ProgramRecord]]:
    """Trace/lower/compile the requested program families (memoized per
    process: the graph, shard and memory suites — and the tier-1 tests —
    share one build of each family)."""
    unknown = set(tags) - set(ALL_TAGS)
    if unknown:
        raise ValueError(f"unknown program tag(s) {sorted(unknown)}; pick from {ALL_TAGS}")
    for family, build in _BUILDERS:
        if any(t in tags and t not in _MEMO for t in family):
            _MEMO.update(build())
    return {t: _MEMO[t] for t in tags}


def clear_memo():
    """Drop the per-process program memo (tests that rebuild with doctored
    configs use this; the CLI never needs it)."""
    _MEMO.clear()
