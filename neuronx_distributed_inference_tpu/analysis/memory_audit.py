"""HBM memory-contract auditor: donation-alias proof + budget-pinned
per-(phase, bucket) accounting.

Two silent HBM catastrophes this suite makes loud, both decided from the
partitioned executables :mod:`.programs` already compiles on CPU:

- **MEM401 donation-alias proof** — ``donate_argnums`` is a REQUEST;
  aliasing is what the compiler actually grants. The compiled module's
  ``input_output_alias`` table must contain EVERY donated cache leaf
  (QuantizedKV code AND scale leaves, across the contiguous, ring-bounded
  and paged cache variants). A cache leaf missing from the table means the
  step double-buffers the largest tensor in the system — at 16k context
  with the quantized cache's 2× block-admission math, that is exactly the
  OOM the pool accounting promised could not happen.
- **MEM402 per-bucket HBM accounting** — a static footprint model per
  (phase, bucket): weight bytes (post-sharding, true dtype including
  int8/fp8 codes) + cache bytes (codes + scales, the same per-leaf math the
  serving pool rides) + the executable's largest live temp (XLA's own
  buffer assignment via ``compiled.memory_analysis()``, with an HLO-text
  scan fallback). Pinned to ``analysis/memory_baseline.json`` with a
  percentage regression gate; ``--json`` carries the per-bucket breakdown
  so bench and docs cite one number.
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import Dict, List, Optional, Set, Tuple

from neuronx_distributed_inference_tpu.analysis import programs
from neuronx_distributed_inference_tpu.analysis.findings import (
    Finding,
    SEV_ERROR,
)

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "memory_baseline.json"

MEMORY_AUDIT_TAGS = programs.ALL_TAGS

#: allowed relative drift per accounting component before MEM402 fires; the
#: committed baseline may override (``tolerance_pct`` key)
DEFAULT_TOLERANCE_PCT = 2.0

_COMPONENTS = ("weights_bytes", "cache_bytes", "temp_bytes", "total_bytes")

_ALIAS_ENTRY_RE = re.compile(r"\((\d+),\s*\{[^}]*\},\s*(?:may|must)-alias\)")

#: set by :func:`run` — the per-bucket breakdown the CLI embeds in --json
_LAST_REPORT: Dict = {}


# ---------------------------------------------------------------------------
# MEM401: donation-alias proof
# ---------------------------------------------------------------------------


def aliased_param_numbers(hlo_text: str) -> Set[int]:
    """Parameter numbers granted aliasing in a compiled module's
    ``input_output_alias`` table (brace-matched: the table nests braces for
    output/parameter tuple indices)."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return set()
    i = start + len("input_output_alias=")
    depth = 0
    end = i
    for j in range(i, len(hlo_text)):
        c = hlo_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                end = j + 1
                break
    table = hlo_text[i:end]
    return {int(m.group(1)) for m in _ALIAS_ENTRY_RE.finditer(table)}


def donation_findings(
    hlo_text: str,
    cache_param_range: Tuple[int, int],
    cache_leaf_paths: List[str],
    location: str,
    key: str,
) -> List[Finding]:
    """MEM401 detector over one compiled module: every flat parameter number
    in ``cache_param_range`` must appear in the alias table. Standalone so
    the proven-detector test can feed it a program compiled with donation
    disabled."""
    aliased = aliased_param_numbers(hlo_text)
    lo, hi = cache_param_range
    missing = [i for i in range(lo, hi) if i not in aliased]
    if not missing:
        return []
    names = [
        cache_leaf_paths[i - lo] if 0 <= i - lo < len(cache_leaf_paths) else str(i)
        for i in missing
    ]
    return [
        Finding(
            rule="MEM401",
            severity=SEV_ERROR,
            location=location,
            message=(
                f"KV-cache donation does NOT alias: {len(missing)} of "
                f"{hi - lo} donated cache leaves are absent from the "
                f"compiled input_output_alias table ({', '.join(names[:6])}"
                f"{'...' if len(names) > 6 else ''}) — the step "
                f"double-buffers the cache; check donate_argnums and that "
                f"the output cache keeps the input's shape/dtype/sharding"
            ),
            key=key,
        )
    ]


def cache_leaf_paths(rec) -> List[str]:
    """Flat cache leaf paths in HLO parameter order (pytree flatten order),
    in the same ``programs.path_str`` format the shard census pins."""
    import jax.tree_util as jtu

    return [
        programs.path_str(path)
        for path, _leaf in jtu.tree_flatten_with_path(rec.cache)[0]
    ]


# ---------------------------------------------------------------------------
# MEM402: static accounting
# ---------------------------------------------------------------------------


def _sharded_bytes(tree, shardings) -> int:
    """Per-device bytes of a committed tree: each leaf's shard shape under
    its realized sharding × the TRUE dtype itemsize (int8/fp8 codes count 1
    byte; fp32 scales count 4)."""
    import jax.tree_util as jtu
    import numpy as np

    total = 0
    for leaf, sh in zip(jtu.tree_leaves(tree), jtu.tree_leaves(shardings)):
        shard_shape = sh.shard_shape(leaf.shape)
        total += int(np.prod(shard_shape, dtype=np.int64)) * leaf.dtype.itemsize
    return int(total)


_OP_CALL_RE = re.compile(r"\s[a-z][\w\-]*\(")


def _largest_temp_from_hlo(hlo_text: str) -> int:
    """Fallback temp estimate when ``memory_analysis`` is unavailable: the
    largest non-parameter RESULT buffer defined in the module (the typed
    result sits between ``" = "`` and the op-name call; operand types after
    the op name are someone else's results or parameters and must not
    count)."""
    from neuronx_distributed_inference_tpu.analysis.shard_audit import (
        _max_buffer_bytes,
    )

    best = 0
    for line in hlo_text.splitlines():
        s = line.strip()
        if " = " not in s or s.startswith("ROOT") or "parameter(" in s:
            continue
        rhs = s.split(" = ", 1)[1]
        m = _OP_CALL_RE.search(rhs)
        result_part = rhs[: m.start()] if m else rhs
        best = max(best, _max_buffer_bytes(result_part))
    return best


def temp_bytes(rec) -> Tuple[int, str]:
    """(largest-live-temp bytes, source) for one compiled program."""
    try:
        ma = rec.compiled.memory_analysis()
        if ma is not None and getattr(ma, "temp_size_in_bytes", None) is not None:
            return int(ma.temp_size_in_bytes), "memory_analysis"
    except Exception:
        pass
    return _largest_temp_from_hlo(rec.compiled_text), "hlo_scan"


def accounting(rec) -> Dict[str, int]:
    """The static per-device HBM footprint model for one (tag, bucket)."""
    weights = _sharded_bytes(rec.params, rec.realized_param_shardings)
    cache = _sharded_bytes(rec.cache, rec.realized_cache_shardings)
    temp, source = temp_bytes(rec)
    return {
        "weights_bytes": weights,
        "cache_bytes": cache,
        "temp_bytes": temp,
        "total_bytes": weights + cache + temp,
        "temp_source": source,
    }


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_memory_baseline(path: Optional[pathlib.Path] = None) -> Dict:
    p = path or BASELINE_PATH
    try:
        with open(p) as f:
            return json.load(f)
    except FileNotFoundError:
        return {}


def save_memory_baseline(data: Dict, path: Optional[pathlib.Path] = None):
    p = path or BASELINE_PATH
    with open(p, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def last_report() -> Dict:
    """Per-bucket breakdown of the most recent :func:`run` (what the CLI
    embeds under ``"memory"`` in --json and renders as the text table)."""
    return dict(_LAST_REPORT)


def render_breakdown(report: Optional[Dict] = None) -> str:
    """Human-readable per-(tag, bucket) HBM table."""
    report = report if report is not None else last_report()
    if not report:
        return ""
    lines = [
        "per-(phase, bucket) HBM accounting (per-device bytes):",
        f"  {'program':<28} {'bucket':>6} {'weights':>10} {'cache':>10} "
        f"{'temp':>10} {'total':>11}",
    ]
    for tag in sorted(report):
        for bucket in sorted(report[tag], key=int):
            row = report[tag][bucket]
            lines.append(
                f"  {tag:<28} {bucket:>6} {row['weights_bytes']:>10} "
                f"{row['cache_bytes']:>10} {row['temp_bytes']:>10} "
                f"{row['total_bytes']:>11}"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def run(
    write_baseline: bool = False,
    baseline_path: Optional[pathlib.Path] = None,
    tags: Tuple[str, ...] = MEMORY_AUDIT_TAGS,
    tolerance_pct: Optional[float] = None,
) -> List[Finding]:
    """Run the memory audit over the requested tags; return findings."""
    global _LAST_REPORT
    findings: List[Finding] = []
    results = programs.collect_programs(tuple(tags))
    baseline = load_memory_baseline(baseline_path)
    tol = (
        tolerance_pct
        if tolerance_pct is not None
        else float(baseline.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))
    )
    base_programs = baseline.get("programs", {})
    observed: Dict[str, Dict[str, Dict[str, int]]] = {}

    for tag, per_bucket in results.items():
        observed[tag] = {}
        for bucket in sorted(per_bucket):
            rec = per_bucket[bucket]
            # -- MEM401 ----------------------------------------------------
            findings.extend(
                donation_findings(
                    rec.compiled_text,
                    rec.cache_param_range,
                    cache_leaf_paths(rec),
                    f"{tag}/{bucket}",
                    tag,
                )
            )
            # -- MEM402 ----------------------------------------------------
            acct = accounting(rec)
            observed[tag][str(bucket)] = acct
            if write_baseline:
                continue
            expected = base_programs.get(tag, {}).get(str(bucket))
            if expected is None:
                findings.append(
                    Finding(
                        rule="MEM402",
                        severity=SEV_ERROR,
                        location=f"{tag}/{bucket}",
                        message=(
                            f"no committed HBM accounting for ({tag}, "
                            f"{bucket}) — run --write-baseline and "
                            f"review/commit memory_baseline.json"
                        ),
                        key=tag,
                    )
                )
                continue
            for comp in _COMPONENTS:
                old = int(expected.get(comp, 0))
                new = int(acct[comp])
                if old == new:
                    continue
                pct = abs(new - old) / max(old, 1) * 100.0
                if pct <= tol:
                    continue
                direction = "grew" if new > old else "shrank"
                findings.append(
                    Finding(
                        rule="MEM402",
                        severity=SEV_ERROR,
                        location=f"{tag}/{bucket}",
                        message=(
                            f"HBM accounting {comp} {direction} "
                            f"{pct:.1f}% vs baseline ({old} -> {new} bytes, "
                            f"tolerance {tol}%) — an intentional footprint "
                            f"change must regenerate memory_baseline.json "
                            f"(--write-baseline) and the diff reviewed; an "
                            f"unintentional one is the regression this gate "
                            f"exists for"
                        ),
                        key=tag,
                    )
                )

    _LAST_REPORT = observed
    if write_baseline:
        merged = dict(load_memory_baseline(baseline_path))
        merged.setdefault("programs", {})
        merged["programs"].update(observed)
        merged["tolerance_pct"] = tol
        save_memory_baseline(merged, baseline_path)
    return findings
