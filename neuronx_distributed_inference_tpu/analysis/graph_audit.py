"""Jaxpr/HLO contract auditor for the compiled sub-model programs.

For each registered sub-model tag × bucket, trace a TINY tp-sharded model on
the CPU mesh (no accelerator needed; 8 virtual devices, same GSPMD path as
hardware) and assert the graph invariants the AOT latency model relies on:

- **GRAPH201 collective-census** — per-phase counts of the partitioner's
  collectives (all-reduce / all-gather / reduce-scatter / collective-permute
  / all-to-all in the compiled HLO) must match the committed baseline
  (``analysis/graph_baseline.json``). A new collective in the decode graph is
  a silent latency regression even when numerics are identical; a missing
  one usually means a sharding constraint stopped propagating.
- **GRAPH202 census-bucket-variance** — the census must be IDENTICAL across
  buckets of one tag: buckets only change constants, never the communication
  pattern.
- **GRAPH203 f32-upcast-in-decode** — in a bf16 config, no
  ``convert_element_type`` bf16→f32 inside the decode layer scan except from
  the allowlisted files (norm/softmax/rope/sampling compute in f32 by
  design; ``cast_logits_fp32`` is outside the scan).
- **GRAPH204 missing-donation** — KV-cache donation must survive to lowering
  (``tf.aliasing_output`` / ``jax.buffer_donor`` attrs on the cache leaves);
  otherwise every decode step double-buffers the whole cache.
- **GRAPH205 bucket-skeleton-drift** — the jaxpr equation skeleton (the
  recursive sequence of primitive names) must be identical across buckets of
  one tag: same program, different constants, exactly the frozen-executable
  contract.

Everything runs from ``jax.make_jaxpr``-level tracing plus a CPU compile of
tiny (2-layer, 64-hidden) models — a few seconds per tag, no device state.
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import Dict, List, Optional, Tuple

from neuronx_distributed_inference_tpu.analysis.findings import (
    Finding,
    SEV_ERROR,
)

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "graph_baseline.json"

COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# Files allowed to upcast bf16 -> f32 inside the decode scan: numerically
# deliberate (fp32 softmax/norm/rope/sampling), mirrored by config flags
# (attention_softmax_fp32) or reference parity. kvcache/block_kvcache are the
# int8/fp8 cache write path: the running-absmax + quantize math runs in f32
# by design (the CACHE itself stays in codes — GRAPH203 would catch a
# dequantized-cache materialization coming from any other file).
F32_UPCAST_ALLOWLIST = (
    "norm.py",
    "attention.py",
    "rope.py",
    "sampling.py",
    "decode_attention.py",
    "masks.py",
    "quant.py",
    "kvcache.py",
    "block_kvcache.py",
)

TAG_CONTEXT_ENCODING = "context_encoding"
TAG_TOKEN_GENERATION = "token_generation"
TAG_FUSED_SPECULATION = "fused_speculation"
# the same CTE/TKG programs compiled with kv_cache_dtype="int8" — the
# quantized-cache program set gets its own census/skeleton/dtype contract
TAG_CONTEXT_ENCODING_KVQ8 = "context_encoding_kvq8"
TAG_TOKEN_GENERATION_KVQ8 = "token_generation_kvq8"

AUDIT_TAGS = (
    TAG_CONTEXT_ENCODING,
    TAG_TOKEN_GENERATION,
    TAG_FUSED_SPECULATION,
    TAG_CONTEXT_ENCODING_KVQ8,
    TAG_TOKEN_GENERATION_KVQ8,
)


# ---------------------------------------------------------------------------
# tiny audit model
# ---------------------------------------------------------------------------


def _tiny_hf_attrs(vocab: int = 128) -> dict:
    return dict(
        model_type="llama",
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=2,
        vocab_size=vocab,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        max_position_embeddings=256,
        hidden_act="silu",
        tie_word_embeddings=False,
    )


def _tiny_config(**tpu_overrides):
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig

    attrs = _tiny_hf_attrs()

    def load_config(cfg):
        for k, v in attrs.items():
            setattr(cfg, k, v)

    tc_kwargs = dict(
        batch_size=2,
        seq_len=128,
        dtype="bfloat16",
        tp_degree=2,
        context_encoding_buckets=[64, 128],
        token_generation_buckets=[64, 128],
    )
    tc_kwargs.update(tpu_overrides)
    return LlamaInferenceConfig(TpuConfig(**tc_kwargs), load_config=load_config)


def _census(hlo_text: str) -> Dict[str, int]:
    counts = {}
    for op in COLLECTIVE_OPS:
        # ops appear as `%all-reduce.12 = ...` / `all-gather-start`; count
        # result definitions so fused start/done pairs count once
        counts[op] = len(re.findall(r"%?" + op + r"(?:-start)?(?:\.\d+)? = ", hlo_text))
    return counts


def _skeleton(jaxpr) -> Tuple:
    """Recursive primitive-name skeleton of a (closed) jaxpr."""
    out = []
    for eqn in jaxpr.eqns:
        sub = []
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                sub.append(_skeleton(inner))
        out.append((eqn.primitive.name, tuple(sub)))
    return tuple(out)


def _eqn_source_file(eqn) -> Optional[str]:
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name
    except Exception:
        pass
    return None


def _walk_scan_upcasts(jaxpr, hits: List[Tuple[str, Optional[str]]], in_scan: bool = False):
    """Collect bf16->f32 convert_element_type eqns inside scan bodies."""
    import jax.numpy as jnp

    for eqn in jaxpr.eqns:
        if in_scan and eqn.primitive.name == "convert_element_type":
            src_dtype = eqn.invars[0].aval.dtype
            dst_dtype = eqn.params.get("new_dtype")
            if src_dtype == jnp.bfloat16 and dst_dtype == jnp.float32:
                hits.append((str(eqn), _eqn_source_file(eqn)))
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                _walk_scan_upcasts(
                    inner, hits, in_scan=in_scan or eqn.primitive.name == "scan"
                )


def _donation_count(lowered_text: str) -> int:
    return lowered_text.count("tf.aliasing_output") + lowered_text.count(
        "jax.buffer_donor"
    )


# ---------------------------------------------------------------------------
# per-tag tracing
# ---------------------------------------------------------------------------


def _audit_causal_lm(kv_quant: bool = False):
    """Trace/lower/compile the CTE and TKG programs across buckets.

    ``kv_quant``: compile the same programs with kv_cache_dtype="int8"
    (codes + scale cache leaves; fused quantize/dequantize in the graph).

    Returns {tag: {bucket: (jaxpr, lowered_text, census, donation_count,
    n_cache_leaves)}}.
    """
    import jax

    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    cfg = _tiny_config(**(dict(kv_cache_dtype="int8") if kv_quant else {}))
    app = TpuModelForCausalLM(None, cfg)
    app.load(random_weights=True)
    results = {}
    for tag, runner in (
        (
            TAG_CONTEXT_ENCODING_KVQ8 if kv_quant else TAG_CONTEXT_ENCODING,
            app.context_encoding_model,
        ),
        (
            TAG_TOKEN_GENERATION_KVQ8 if kv_quant else TAG_TOKEN_GENERATION,
            app.token_generation_model,
        ),
    ):
        per_bucket = {}
        n_cache_leaves = len(jax.tree.leaves(app.kv_cache))
        for bucket in runner.buckets:
            inputs = runner.example_inputs(bucket)
            with jax.set_mesh(app.mesh):
                traced = runner._fn.trace(app.params, app.kv_cache, inputs, None)
                lowered = traced.lower()
                compiled = lowered.compile()
            lowered_text = lowered.as_text()
            per_bucket[bucket] = (
                traced.jaxpr,
                lowered_text,
                _census(compiled.as_text()),
                _donation_count(lowered_text),
                n_cache_leaves,
            )
        results[tag] = per_bucket
    return results


def _audit_fused_spec():
    """Trace/lower/compile the fused-speculation decode program across ≥2
    TKG bucket widths (draft chain + target verify in ONE graph)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from neuronx_distributed_inference_tpu.config import (
        FusedSpecConfig,
        OnDeviceSamplingConfig,
    )
    from neuronx_distributed_inference_tpu.models.base import StepInputs
    from neuronx_distributed_inference_tpu.modules.sampling import (
        prepare_sampling_params,
    )
    from neuronx_distributed_inference_tpu.runtime.fused_spec import (
        TpuFusedSpecModelForCausalLM,
    )

    cfg = _tiny_config(
        speculation_length=3,
        enable_fused_speculation=True,
        on_device_sampling_config=OnDeviceSamplingConfig(do_sample=False),
    )
    cfg.fused_spec_config = FusedSpecConfig(
        draft_model_name="tiny-draft", draft_config=_tiny_config()
    )
    app = TpuFusedSpecModelForCausalLM(None, cfg)
    app.load(random_weights=True)

    B = cfg.tpu_config.batch_size
    sp = prepare_sampling_params(B)
    per_bucket = {}
    n_cache_leaves = len(jax.tree.leaves(app.draft_cache)) + len(
        jax.tree.leaves(app.target_cache)
    )
    for bucket in app.tkg_buckets:
        inputs = StepInputs(
            input_ids=jnp.zeros((B, 1), jnp.int32),
            attention_mask=jnp.zeros((B, bucket), jnp.int32),
            position_ids=jnp.full((B, 1), 7, jnp.int32),
            seq_ids=jnp.asarray(np.arange(B, dtype=np.int32)),
            sampling_params=jnp.asarray(sp, jnp.float32),
        )
        with jax.set_mesh(app.mesh):
            traced = app._tkg_fn.trace(
                app.draft_params, app.target_params, app.draft_cache,
                app.target_cache, inputs, None,
            )
            lowered = traced.lower()
            compiled = lowered.compile()
        lowered_text = lowered.as_text()
        per_bucket[bucket] = (
            traced.jaxpr,
            lowered_text,
            _census(compiled.as_text()),
            _donation_count(lowered_text),
            n_cache_leaves,
        )
    return {TAG_FUSED_SPECULATION: per_bucket}


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def load_census_baseline(path: Optional[pathlib.Path] = None) -> Dict[str, Dict[str, int]]:
    p = path or BASELINE_PATH
    try:
        with open(p) as f:
            return json.load(f).get("census", {})
    except FileNotFoundError:
        return {}


def save_census_baseline(census: Dict[str, Dict[str, int]], path: Optional[pathlib.Path] = None):
    p = path or BASELINE_PATH
    with open(p, "w") as f:
        json.dump({"census": census}, f, indent=2, sort_keys=True)
        f.write("\n")


def run(
    write_baseline: bool = False,
    baseline_path: Optional[pathlib.Path] = None,
    tags: Tuple[str, ...] = AUDIT_TAGS,
) -> List[Finding]:
    """Run the graph audit over the requested tags; return findings."""
    findings: List[Finding] = []
    results = {}
    if TAG_CONTEXT_ENCODING in tags or TAG_TOKEN_GENERATION in tags:
        results.update(_audit_causal_lm())
    if TAG_FUSED_SPECULATION in tags:
        results.update(_audit_fused_spec())
    if TAG_CONTEXT_ENCODING_KVQ8 in tags or TAG_TOKEN_GENERATION_KVQ8 in tags:
        results.update(_audit_causal_lm(kv_quant=True))
    results = {t: results[t] for t in tags if t in results}

    baseline = load_census_baseline(baseline_path)
    observed_census: Dict[str, Dict[str, int]] = {}

    for tag, per_bucket in results.items():
        buckets = sorted(per_bucket)
        # -- GRAPH204 donation ---------------------------------------------
        for bucket in buckets:
            _, _, _, donated, n_cache = per_bucket[bucket]
            if donated < n_cache:
                findings.append(
                    Finding(
                        rule="GRAPH204",
                        severity=SEV_ERROR,
                        location=f"{tag}/{bucket}",
                        message=(
                            f"KV-cache donation missing: {donated} aliased/"
                            f"donor buffers in the lowering, expected ≥ "
                            f"{n_cache} cache leaves — decode would "
                            f"double-buffer the cache"
                        ),
                        key=tag,
                    )
                )
        # -- GRAPH202/201 census -------------------------------------------
        censuses = {b: per_bucket[b][2] for b in buckets}
        ref_bucket = buckets[0]
        for b in buckets[1:]:
            if censuses[b] != censuses[ref_bucket]:
                findings.append(
                    Finding(
                        rule="GRAPH202",
                        severity=SEV_ERROR,
                        location=f"{tag}/{b}",
                        message=(
                            f"collective census differs across buckets: "
                            f"{censuses[ref_bucket]} (bucket {ref_bucket}) vs "
                            f"{censuses[b]} (bucket {b}) — buckets must only "
                            f"change constants, never the communication "
                            f"pattern"
                        ),
                        key=tag,
                    )
                )
        observed_census[tag] = censuses[ref_bucket]
        # under --write-baseline the observed census IS the new contract:
        # drift vs the old file is being accepted, not reported
        expected = None if write_baseline else baseline.get(tag)
        if expected is not None and expected != censuses[ref_bucket]:
            regressed = {
                op: (expected.get(op, 0), censuses[ref_bucket].get(op, 0))
                for op in set(expected) | set(censuses[ref_bucket])
                if expected.get(op, 0) != censuses[ref_bucket].get(op, 0)
            }
            findings.append(
                Finding(
                    rule="GRAPH201",
                    severity=SEV_ERROR,
                    location=f"{tag}/{ref_bucket}",
                    message=(
                        f"collective census drifted from baseline "
                        f"(op: expected -> got): {regressed} — regenerate "
                        f"with --write-baseline only if the change is "
                        f"intentional"
                    ),
                    key=tag,
                )
            )
        # -- GRAPH205 skeleton ---------------------------------------------
        skels = {b: _skeleton(per_bucket[b][0].jaxpr) for b in buckets}
        for b in buckets[1:]:
            if skels[b] != skels[ref_bucket]:
                findings.append(
                    Finding(
                        rule="GRAPH205",
                        severity=SEV_ERROR,
                        location=f"{tag}/{b}",
                        message=(
                            f"jaxpr equation skeleton differs between "
                            f"buckets {ref_bucket} and {b} — the per-bucket "
                            f"programs must share one structure (only "
                            f"constants may differ)"
                        ),
                        key=tag,
                    )
                )
        # -- GRAPH203 f32 upcasts in decode scan ---------------------------
        if tag in (
            TAG_TOKEN_GENERATION,
            TAG_FUSED_SPECULATION,
            TAG_TOKEN_GENERATION_KVQ8,
        ):
            hits: List[Tuple[str, Optional[str]]] = []
            _walk_scan_upcasts(per_bucket[ref_bucket][0].jaxpr, hits)
            for eqn_str, src in hits:
                base = pathlib.Path(src).name if src else "<unknown>"
                if src is not None and base in F32_UPCAST_ALLOWLIST:
                    continue
                if src is None:
                    # no user frame (jax-internal rewrite): not actionable
                    continue
                findings.append(
                    Finding(
                        rule="GRAPH203",
                        severity=SEV_ERROR,
                        location=f"{tag}/{ref_bucket}",
                        message=(
                            f"bf16→f32 upcast inside the decode layer scan "
                            f"from {base} (not in the logits/norm allowlist): "
                            f"{eqn_str[:120]}"
                        ),
                        key=tag,
                    )
                )

    if write_baseline:
        # merge over the existing file so auditing a tags SUBSET never
        # deletes the other tags' committed censuses
        merged = dict(baseline)
        merged.update(observed_census)
        save_census_baseline(merged, baseline_path)
    return findings
