"""Jaxpr/HLO contract auditor for the compiled sub-model programs.

For each registered sub-model tag × bucket, trace a TINY tp-sharded model on
the CPU mesh (no accelerator needed; 8 virtual devices, same GSPMD path as
hardware) and assert the graph invariants the AOT latency model relies on:

- **GRAPH201 collective-census** — per-phase counts of the partitioner's
  collectives (all-reduce / all-gather / reduce-scatter / collective-permute
  / all-to-all in the compiled HLO) must match the committed baseline
  (``analysis/graph_baseline.json``). A new collective in the decode graph is
  a silent latency regression even when numerics are identical; a missing
  one usually means a sharding constraint stopped propagating.
- **GRAPH202 census-bucket-variance** — the census must be IDENTICAL across
  buckets of one tag: buckets only change constants, never the communication
  pattern.
- **GRAPH203 f32-upcast-in-decode** — in a bf16 config, no
  ``convert_element_type`` bf16→f32 inside the decode layer scan except from
  the allowlisted files (norm/softmax/rope/sampling compute in f32 by
  design; ``cast_logits_fp32`` is outside the scan).
- **GRAPH204 missing-donation** — KV-cache donation must survive to lowering
  (``tf.aliasing_output`` / ``jax.buffer_donor`` attrs on the cache leaves);
  otherwise every decode step double-buffers the whole cache. The memory
  audit (MEM401, ``memory_audit.py``) carries this further: the COMPILED
  executable's ``input_output_alias`` table must actually alias every
  donated cache leaf.
- **GRAPH205 bucket-skeleton-drift** — the jaxpr equation skeleton (the
  recursive sequence of primitive names) must be identical across buckets of
  one tag: same program, different constants, exactly the frozen-executable
  contract.

Program construction (tiny 2-layer models, CPU compile, a few seconds per
tag) lives in :mod:`.programs` and is SHARED with the shard and memory
audits — the three suites trace each program family once per process.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, List, Optional, Tuple

from neuronx_distributed_inference_tpu.analysis import programs
from neuronx_distributed_inference_tpu.analysis.findings import (
    Finding,
    SEV_ERROR,
)
from neuronx_distributed_inference_tpu.analysis.programs import (  # noqa: F401
    COLLECTIVE_OPS,
    TAG_CONTEXT_ENCODING,
    TAG_CONTEXT_ENCODING_KVQ8,
    TAG_FUSED_SPECULATION,
    TAG_FUSED_SPECULATION_KVQ8,
    TAG_TOKEN_GENERATION,
    TAG_TOKEN_GENERATION_KVQ8,
    tiny_config as _tiny_config,
)

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "graph_baseline.json"

# Files allowed to upcast bf16 -> f32 inside the decode scan: numerically
# deliberate (fp32 softmax/norm/rope/sampling), mirrored by config flags
# (attention_softmax_fp32) or reference parity. kvcache/block_kvcache are the
# int8/fp8 cache write path: the running-absmax + quantize math runs in f32
# by design (the CACHE itself stays in codes — GRAPH203 would catch a
# dequantized-cache materialization coming from any other file).
F32_UPCAST_ALLOWLIST = (
    "norm.py",
    "attention.py",
    "rope.py",
    "sampling.py",
    "decode_attention.py",
    "ragged_paged_attention.py",
    "masks.py",
    "quant.py",
    "kvcache.py",
    "block_kvcache.py",
)

AUDIT_TAGS = programs.COMMITTED_TAGS


# ---------------------------------------------------------------------------
# jaxpr walks
# ---------------------------------------------------------------------------


def _skeleton(jaxpr) -> Tuple:
    """Recursive primitive-name skeleton of a (closed) jaxpr."""
    out = []
    for eqn in jaxpr.eqns:
        sub = []
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                sub.append(_skeleton(inner))
        out.append((eqn.primitive.name, tuple(sub)))
    return tuple(out)


def _eqn_source_file(eqn) -> Optional[str]:
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name
    except Exception:
        pass
    return None


def _walk_scan_upcasts(jaxpr, hits: List[Tuple[str, Optional[str]]], in_scan: bool = False):
    """Collect bf16->f32 convert_element_type eqns inside scan bodies."""
    import jax.numpy as jnp

    for eqn in jaxpr.eqns:
        if in_scan and eqn.primitive.name == "convert_element_type":
            src_dtype = eqn.invars[0].aval.dtype
            dst_dtype = eqn.params.get("new_dtype")
            if src_dtype == jnp.bfloat16 and dst_dtype == jnp.float32:
                hits.append((str(eqn), _eqn_source_file(eqn)))
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                _walk_scan_upcasts(
                    inner, hits, in_scan=in_scan or eqn.primitive.name == "scan"
                )


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def load_census_baseline(path: Optional[pathlib.Path] = None) -> Dict[str, Dict[str, int]]:
    p = path or BASELINE_PATH
    try:
        with open(p) as f:
            return json.load(f).get("census", {})
    except FileNotFoundError:
        return {}


def save_census_baseline(census: Dict[str, Dict[str, int]], path: Optional[pathlib.Path] = None):
    p = path or BASELINE_PATH
    with open(p, "w") as f:
        json.dump({"census": census}, f, indent=2, sort_keys=True)
        f.write("\n")


def run(
    write_baseline: bool = False,
    baseline_path: Optional[pathlib.Path] = None,
    tags: Tuple[str, ...] = AUDIT_TAGS,
) -> List[Finding]:
    """Run the graph audit over the requested tags; return findings."""
    findings: List[Finding] = []
    results = programs.collect_programs(tuple(tags))

    baseline = load_census_baseline(baseline_path)
    observed_census: Dict[str, Dict[str, int]] = {}

    for tag, per_bucket in results.items():
        buckets = sorted(per_bucket)
        # -- GRAPH204 donation ---------------------------------------------
        for bucket in buckets:
            rec = per_bucket[bucket]
            if rec.donation_count < rec.n_cache_leaves:
                findings.append(
                    Finding(
                        rule="GRAPH204",
                        severity=SEV_ERROR,
                        location=f"{tag}/{bucket}",
                        message=(
                            f"KV-cache donation missing: {rec.donation_count} "
                            f"aliased/donor buffers in the lowering, expected "
                            f"≥ {rec.n_cache_leaves} cache leaves — decode "
                            f"would double-buffer the cache"
                        ),
                        key=tag,
                    )
                )
        # -- GRAPH202/201 census -------------------------------------------
        censuses = {b: per_bucket[b].census for b in buckets}
        ref_bucket = buckets[0]
        for b in buckets[1:]:
            if censuses[b] != censuses[ref_bucket]:
                findings.append(
                    Finding(
                        rule="GRAPH202",
                        severity=SEV_ERROR,
                        location=f"{tag}/{b}",
                        message=(
                            f"collective census differs across buckets: "
                            f"{censuses[ref_bucket]} (bucket {ref_bucket}) vs "
                            f"{censuses[b]} (bucket {b}) — buckets must only "
                            f"change constants, never the communication "
                            f"pattern"
                        ),
                        key=tag,
                    )
                )
        observed_census[tag] = censuses[ref_bucket]
        # under --write-baseline the observed census IS the new contract:
        # drift vs the old file is being accepted, not reported
        expected = None if write_baseline else baseline.get(tag)
        if expected is not None and expected != censuses[ref_bucket]:
            regressed = {
                op: (expected.get(op, 0), censuses[ref_bucket].get(op, 0))
                for op in set(expected) | set(censuses[ref_bucket])
                if expected.get(op, 0) != censuses[ref_bucket].get(op, 0)
            }
            findings.append(
                Finding(
                    rule="GRAPH201",
                    severity=SEV_ERROR,
                    location=f"{tag}/{ref_bucket}",
                    message=(
                        f"collective census drifted from baseline "
                        f"(op: expected -> got): {regressed} — regenerate "
                        f"with --write-baseline only if the change is "
                        f"intentional"
                    ),
                    key=tag,
                )
            )
        # -- GRAPH205 skeleton ---------------------------------------------
        skels = {b: _skeleton(per_bucket[b].jaxpr.jaxpr) for b in buckets}
        for b in buckets[1:]:
            if skels[b] != skels[ref_bucket]:
                findings.append(
                    Finding(
                        rule="GRAPH205",
                        severity=SEV_ERROR,
                        location=f"{tag}/{b}",
                        message=(
                            f"jaxpr equation skeleton differs between "
                            f"buckets {ref_bucket} and {b} — the per-bucket "
                            f"programs must share one structure (only "
                            f"constants may differ)"
                        ),
                        key=tag,
                    )
                )
        # -- GRAPH203 f32 upcasts in decode scan ---------------------------
        if tag in (
            TAG_TOKEN_GENERATION,
            TAG_FUSED_SPECULATION,
            TAG_TOKEN_GENERATION_KVQ8,
            TAG_FUSED_SPECULATION_KVQ8,
            programs.TAG_MIXED_STEP,
            programs.TAG_MIXED_STEP_SPEC,
        ):
            hits: List[Tuple[str, Optional[str]]] = []
            _walk_scan_upcasts(per_bucket[ref_bucket].jaxpr.jaxpr, hits)
            for eqn_str, src in hits:
                base = pathlib.Path(src).name if src else "<unknown>"
                if src is not None and base in F32_UPCAST_ALLOWLIST:
                    continue
                if src is None:
                    # no user frame (jax-internal rewrite): not actionable
                    continue
                findings.append(
                    Finding(
                        rule="GRAPH203",
                        severity=SEV_ERROR,
                        location=f"{tag}/{ref_bucket}",
                        message=(
                            f"bf16→f32 upcast inside the decode layer scan "
                            f"from {base} (not in the logits/norm allowlist): "
                            f"{eqn_str[:120]}"
                        ),
                        key=tag,
                    )
                )

    if write_baseline:
        # merge over the existing file so auditing a tags SUBSET never
        # deletes the other tags' committed censuses
        merged = dict(baseline)
        merged.update(observed_census)
        save_census_baseline(merged, baseline_path)
    return findings
