"""Config-flag audit: no silently-ignored feature flags (VERDICT r1 weak #4).

Every :class:`~..config.TpuConfig` / :class:`~..config.MoETpuConfig` field
must be (a) consumed outside ``config.py``, (b) raise when set to a non-inert
value (the ``UNIMPLEMENTED_FLAGS`` contract), or (c) sit on the explicit
allowlist below with a written justification. A field in none of the three
buckets is config-surface padding and yields a **FLAG301** finding.

This is the generalized form of the original private scan in
``tests/test_flag_audit.py``; the test now consumes these findings so the
flag audit, tpulint, and the graph audit share one finding/baseline format
and one CLI (``python -m neuronx_distributed_inference_tpu.analysis``).
"""

from __future__ import annotations

import dataclasses
import pathlib
import re
from typing import Dict, List, Optional

from neuronx_distributed_inference_tpu.analysis.findings import Finding, SEV_ERROR

# Documented pass-through fields: justification required.
ALLOWLIST: Dict[str, str] = {
    # reference parity: the reference also only plumbs pp_degree (SURVEY §2.9)
    "pp_degree": "reference parity; only plumbed, like the reference",
    # multi-host rank bookkeeping, consumed by launch scripts not the graph
    "start_rank_id": "multi-host rank bookkeeping for launch scripts",
    "local_ranks_size": "multi-host rank bookkeeping for launch scripts",
    # inert data containers gated by their feature flag (is_chunked_prefill)
    "chunked_prefill_config": "inert container gated by is_chunked_prefill",
    # consumed by blockwise quantization (gated by quantization_type)
    "blockwise_matmul_block_size": "consumed by blockwise quantization",
    # hardware knobs with no TPU meaning, kept for config-file compatibility;
    # documented as no-ops at their definition
    "logical_nc_config": "NKI hardware knob; documented no-op on TPU",
    "scratchpad_page_size": "NKI hardware knob; documented no-op on TPU",
    # validated against derived values in validate() (must match tp/ep)
    "moe_tp_degree": "validated against tp/ep in validate()",
    "moe_ep_degree": "validated against tp/ep in validate()",
    # validated (non-GLU raises) in MoETpuConfig.validate
    "glu_mlp": "validated in MoETpuConfig.validate",
    "glu_type": "validated in MoETpuConfig.validate",
    # declarative aliases for the cp-axis flash-decode path: validate()
    # requires cp_degree>1 / num_cores_per_group==cp_degree; the S-sharded KV
    # decode itself is implemented off cp_degree (modules/kvcache.py)
    "flash_decoding_enabled": "declarative alias validated against cp_degree",
    "num_cores_per_group": "declarative alias validated against cp_degree",
}


def _package_source_without_config(root: Optional[pathlib.Path] = None) -> str:
    pkg = (
        root
        if root is not None
        else pathlib.Path(__file__).resolve().parents[1]
    )
    srcs = []
    for p in pkg.rglob("*.py"):
        if p.name != "config.py":
            srcs.append(p.read_text())
    return "\n".join(srcs)


def run(root: Optional[pathlib.Path] = None) -> List[Finding]:
    """Audit every config field; return FLAG301 findings for orphans."""
    from neuronx_distributed_inference_tpu.config import (
        MoETpuConfig,
        UNIMPLEMENTED_FLAGS,
        UNIMPLEMENTED_MOE_FLAGS,
    )

    src = _package_source_without_config(root)
    raising = set(UNIMPLEMENTED_FLAGS) | set(UNIMPLEMENTED_MOE_FLAGS)
    findings: List[Finding] = []
    # MoETpuConfig subclasses TpuConfig, so its fields() cover both
    for f in dataclasses.fields(MoETpuConfig):
        name = f.name
        if name in raising or name in ALLOWLIST:
            continue
        if not re.search(r"\b" + re.escape(name) + r"\b", src):
            findings.append(
                Finding(
                    rule="FLAG301",
                    severity=SEV_ERROR,
                    location=f"config.py:{name}",
                    message=(
                        f"TpuConfig field `{name}` is neither consumed "
                        f"outside config.py, raising (UNIMPLEMENTED_FLAGS), "
                        f"nor allowlisted — a silently-ignored feature flag"
                    ),
                    key=name,
                )
            )
    return findings
