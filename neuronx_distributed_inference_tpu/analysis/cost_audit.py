"""Static roofline cost auditor: per-(family, bucket) FLOPs / HBM bytes /
collective bytes, pinned with regression gates.

Every committed program's compute cost is derived STATICALLY from the same
:class:`~.programs.ProgramRecord` harness the graph/shard/memory audits
ride — no TPU in the container, same GSPMD path hardware takes:

- **dot/conv FLOPs** — a jaxpr walk over ``dot_general`` /
  ``conv_general_dilated`` equations, scan bodies multiplied by their trip
  count (the layer scan), cond branches taken at their max. This is the
  arithmetic the MXU must execute per dispatch.
- **HBM traffic** (lower bound, per device, TRUE dtypes) — weight bytes
  (realized shard shapes: int8 codes count 1 byte), cache bytes touched
  (per-token cost = leaf bytes / capacity tokens, read at the bucket's kv
  width, written at the dispatch's query tokens — the same narrow-dtype
  math the serving pool accounting uses), and activation bytes (the
  residual stream: q_tokens × hidden × 2 per layer, read+write, plus the
  fp32 logits row). Cross-checked against
  ``compiled.memory_analysis()``: the model's RESIDENT weight + cache
  bytes (full shard-shape true-dtype sizes, the accounting the traffic
  model is derived from) must not exceed what XLA's own buffer assignment
  says all the arguments occupy.
- **collective bytes** — the existing collective census
  (:func:`programs.census`) extended with bytes: each collective's result
  buffer size in the compiled HLO, summed per op.

From those, a lower-bound step time and tok/s per program are projected
against the :mod:`.device_model` registry (nameplate peak FLOPs by dtype,
HBM GB/s, ICI GB/s) — the measured-vs-predicted baseline hardware session
zero validates.

Rules (all errors, MEM402-style baseline workflow with ``--write-baseline``
unified diffs against ``analysis/cost_baseline.json``):

- **COST501 cost census** — flops / weights / cache-read / cache-write /
  activation / collective bytes per (tag, bucket) within ``tolerance_pct``
  of the committed baseline. A dequant that materializes a cache-sized
  f32 tensor, a new collective, an attention change that doubles FLOPs —
  all land here as a reviewable diff instead of prose.
- **COST502 bucket-scaling sanity** — decode-phase FLOPs and bytes must
  scale (sub-)linearly in the kv/bucket axis:
  ``f(W2) <= f(W1) · (W2/W1) · margin``. An accidental O(T²) term in a
  TKG/mixed program (e.g. decode attending (W, W) instead of (1, W))
  trips the gate.
- **COST503 mixed-step ragged efficiency** — the packing contract of the
  mixed family (q tile, slot count, per-bucket all-decode compute/useful
  ratio) is pinned; a RAGGED_Q_TILE or row-capacity change that degrades
  packing efficiency needs a reviewed baseline change.
- **COST504 arithmetic-intensity classification** — each program's
  compute- vs bandwidth-bound regime (FLOPs/byte vs the device ridge) is
  pinned; a dequant/layout change that flips a program's regime needs a
  reviewed baseline change.
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import Dict, List, Optional, Tuple

from neuronx_distributed_inference_tpu.analysis import device_model, programs
from neuronx_distributed_inference_tpu.analysis.findings import (
    Finding,
    SEV_ERROR,
)

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "cost_baseline.json"

COST_AUDIT_TAGS = programs.ALL_TAGS

#: allowed relative drift per census component before COST501 fires; the
#: committed baseline may override (``tolerance_pct`` key)
DEFAULT_TOLERANCE_PCT = 5.0

#: COST502 superlinearity margin: decode cost may grow at most ~linearly in
#: the bucket axis (the constant weight term makes true decode sublinear)
SCALING_MARGIN = 1.05

_COMPONENTS = (
    "flops",
    "weights_bytes",
    "cache_read_bytes",
    "cache_write_bytes",
    "act_bytes",
    "collective_bytes",
)

#: set by :func:`run` — the per-bucket cost breakdown the CLI embeds under
#: ``"cost"`` in --json and renders as the text table
_LAST_REPORT: Dict = {}


# ---------------------------------------------------------------------------
# FLOPs: jaxpr walk
# ---------------------------------------------------------------------------


def _prod(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _dot_flops(eqn) -> int:
    """2 · |output| · |contracting dims| for one dot_general equation."""
    (lhs_contract, _), _ = eqn.params["dimension_numbers"]
    lhs_shape = eqn.invars[0].aval.shape
    k = 1
    for d in lhs_contract:
        k *= int(lhs_shape[d])
    return 2 * _prod(eqn.outvars[0].aval.shape) * k


def _conv_flops(eqn) -> int:
    """2 · |output| · (kernel spatial · in-channels / groups)."""
    rhs_shape = eqn.invars[1].aval.shape
    # the product over every rhs dim except the output-feature dim is
    # exactly kernel-spatial × in-channels/groups — grouping is already
    # accounted for by the rhs shape
    dn = eqn.params["dimension_numbers"]
    out_feature_dim = dn.rhs_spec[0] if hasattr(dn, "rhs_spec") else 0
    k = _prod(rhs_shape) // max(1, int(rhs_shape[out_feature_dim]))
    return 2 * _prod(eqn.outvars[0].aval.shape) * k


def _sub_jaxprs(params) -> List[Tuple[object, bool]]:
    """(closed-or-open jaxpr, is_branch) pairs nested in an eqn's params —
    covers scan/pjit/while (``jaxpr``-valued params) and cond branch
    tuples."""
    out = []
    for v in params.values():
        if getattr(v, "jaxpr", None) is not None or hasattr(v, "eqns"):
            out.append((v, False))
        elif isinstance(v, (tuple, list)):
            branches = [b for b in v if getattr(b, "jaxpr", None) is not None]
            out.extend((b, True) for b in branches)
    return out


def _open(j):
    return j.jaxpr if getattr(j, "jaxpr", None) is not None else j


def jaxpr_flops(jaxpr, multiplier: int = 1) -> int:
    """Total dot/conv FLOPs of a (closed) jaxpr, scan bodies multiplied by
    their static trip count, cond branches counted at their max (the
    executed-path upper bound among branches, a lower bound stays exact
    when branches match)."""
    jaxpr = _open(jaxpr)
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += multiplier * _dot_flops(eqn)
            continue
        if name == "conv_general_dilated":
            total += multiplier * _conv_flops(eqn)
            continue
        inner_mult = multiplier
        if name == "scan":
            inner_mult = multiplier * int(eqn.params.get("length", 1))
        branch_flops = []
        for sub, is_branch in _sub_jaxprs(eqn.params):
            f = jaxpr_flops(sub, inner_mult)
            if is_branch:
                branch_flops.append(f)
            else:
                total += f
        if branch_flops:
            total += max(branch_flops)
    return total


# ---------------------------------------------------------------------------
# bytes: HBM traffic model
# ---------------------------------------------------------------------------


def _shard_bytes(leaf, sharding) -> int:
    # ONE implementation of shard-shape × true-dtype byte accounting across
    # the memory and cost suites (memory_audit._sharded_bytes takes trees;
    # single-leaf lists are trees)
    from neuronx_distributed_inference_tpu.analysis.memory_audit import (
        _sharded_bytes,
    )

    return _sharded_bytes([leaf], [sharding])


def weights_bytes(rec) -> int:
    """Per-device weight bytes in TRUE dtype (int8 codes count 1 byte) —
    the weight stream a decode step reads once. Same per-leaf math as the
    memory audit's MEM402 accounting, by construction."""
    from neuronx_distributed_inference_tpu.analysis.memory_audit import (
        _sharded_bytes,
    )

    return _sharded_bytes(rec.params, rec.realized_param_shardings)


def cache_traffic(rec) -> Tuple[int, int]:
    """(read, write) cache bytes per dispatch, per device, TRUE dtype.

    Data leaves (ndim >= 4) are priced per token slot — leaf shard bytes /
    capacity tokens — read at rows × kv_width and written at q_tokens,
    clamped to the leaf itself. Scale leaves ((L, H) floats) are read
    whole: they are noise next to the code stream but belong in the model.
    """
    import jax.tree_util as jtu

    meta = rec.shape_meta
    read = write = 0.0
    for leaf, sh in zip(
        jtu.tree_leaves(rec.cache), jtu.tree_leaves(rec.realized_cache_shardings)
    ):
        nbytes = _shard_bytes(leaf, sh)
        if getattr(leaf, "ndim", 0) >= 4 and meta.cache_capacity_tokens:
            per_token = nbytes / meta.cache_capacity_tokens
            read += min(nbytes, per_token * meta.rows * meta.kv_width)
            write += min(nbytes, per_token * meta.q_tokens)
        else:
            read += nbytes
    return int(read), int(write)


def act_bytes(rec) -> int:
    """Residual-stream traffic model: the (q_tokens, hidden) bf16 hidden
    state crosses HBM twice per layer (read + write at the layer boundary;
    everything inside a layer is XLA-fused), plus the fp32 logits row per
    batch row. A lower bound — attention intermediates never materialize
    on the kernel paths."""
    meta = rec.shape_meta
    return int(
        meta.q_tokens * meta.hidden * 2 * meta.layers * 2
        + meta.rows * meta.vocab * 4
    )


# ---------------------------------------------------------------------------
# collective bytes (rides the census)
# ---------------------------------------------------------------------------

_COLLECTIVE_DEF_RE = {
    op: re.compile(r"%?" + op + r"(?:-start)?(?:\.\d+)? = ")
    for op in programs.COLLECTIVE_OPS
}


def collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Bytes per collective op in a compiled module: the largest buffer in
    each collective's RESULT type (for ``-start`` tuples that is the
    gathered output), summed per op — the existing census with bytes
    attached."""
    from neuronx_distributed_inference_tpu.analysis.shard_audit import (
        _max_buffer_bytes,
    )

    out = {op: 0 for op in programs.COLLECTIVE_OPS}
    for line in hlo_text.splitlines():
        s = line.strip()
        if " = " not in s:
            continue
        for op in programs.COLLECTIVE_OPS:
            if f" {op}(" not in s and f" {op}-start(" not in s:
                continue
            if not _COLLECTIVE_DEF_RE[op].search(s):
                continue
            rhs = s.split(" = ", 1)[1]
            result_part = rhs.split(op, 1)[0]
            out[op] += _max_buffer_bytes(result_part)
            break
    return out


# ---------------------------------------------------------------------------
# the census + projection for one record
# ---------------------------------------------------------------------------


def _model_group(rec) -> int:
    from neuronx_distributed_inference_tpu.analysis.shard_audit import (
        _model_group_size,
    )

    return _model_group_size(rec.mesh)


def _hlo_argument_bytes(rec) -> Optional[int]:
    try:
        ma = rec.compiled.memory_analysis()
        v = getattr(ma, "argument_size_in_bytes", None)
        return int(v) if v is not None else None
    except Exception:
        return None


def cost_census(rec) -> Dict:
    """The full static cost record for one (tag, bucket) program."""
    meta = rec.shape_meta
    if meta is None:
        raise ValueError(f"{rec.tag}/{rec.bucket}: ProgramRecord has no shape_meta")
    group = _model_group(rec)
    from neuronx_distributed_inference_tpu.analysis.memory_audit import (
        _sharded_bytes,
    )

    flops = jaxpr_flops(rec.jaxpr)
    w = weights_bytes(rec)
    cache_resident = _sharded_bytes(rec.cache, rec.realized_cache_shardings)
    cr, cw = cache_traffic(rec)
    act = act_bytes(rec)
    coll = collective_bytes(rec.compiled_text)
    hbm = w + cr + cw + act
    flops_dev = flops // max(1, group)
    spec = device_model.get_device()
    t_flops = flops_dev / spec.peak("bfloat16")
    t_hbm = hbm / spec.hbm_bw
    t_ici = sum(coll.values()) / spec.ici_bw
    t_step = max(t_flops, t_hbm, t_ici)
    # tok_s_ub is an UPPER bound: CTE processes its whole prompt, decode
    # commits one token per row, and a fused-speculation step commits up to
    # spec_len+1 tokens per row at full acceptance
    useful = (
        meta.q_tokens
        if rec.phase == programs.PHASE_CTE
        else meta.rows * (meta.spec_len + 1)
    )
    intensity = flops_dev / max(1, hbm)
    return {
        "flops": int(flops),
        "flops_per_device": int(flops_dev),
        "weights_bytes": int(w),
        "cache_read_bytes": int(cr),
        "cache_write_bytes": int(cw),
        "act_bytes": int(act),
        "hbm_bytes": int(hbm),
        "cache_resident_bytes": int(cache_resident),
        "collective_bytes": int(sum(coll.values())),
        "collective_bytes_by_op": {k: v for k, v in coll.items() if v},
        "hlo_argument_bytes": _hlo_argument_bytes(rec),
        "intensity_flops_per_byte": round(intensity, 3),
        "classification": (
            "compute" if intensity >= spec.ridge_flops_per_byte else "bandwidth"
        ),
        "projection": {
            "device": spec.name,
            "t_flops_us": round(t_flops * 1e6, 3),
            "t_hbm_us": round(t_hbm * 1e6, 3),
            "t_ici_us": round(t_ici * 1e6, 3),
            "t_step_lb_us": round(t_step * 1e6, 3),
            "tok_s_ub": round(useful / t_step, 1) if t_step else None,
        },
    }


# ---------------------------------------------------------------------------
# COST502: bucket-scaling sanity (pure, for the proven-detector test)
# ---------------------------------------------------------------------------


def scaling_findings(
    tag: str,
    per_bucket: Dict[int, Dict[str, int]],
    margin: float = SCALING_MARGIN,
) -> List[Finding]:
    """Decode-phase cost must scale (sub-)linearly in the bucket axis:
    for consecutive buckets W1 < W2, f(W2) <= f(W1) · (W2/W1) · margin for
    FLOPs and every byte component. The constant weight term makes real
    decode strictly sublinear; an O(T²) term (decode attending (W, W))
    makes it superlinear and trips."""
    findings: List[Finding] = []
    buckets = sorted(per_bucket)
    for w1, w2 in zip(buckets, buckets[1:]):
        ratio = w2 / w1
        for comp in ("flops", "cache_read_bytes", "act_bytes"):
            f1 = per_bucket[w1].get(comp, 0)
            f2 = per_bucket[w2].get(comp, 0)
            if f1 <= 0:
                continue
            if f2 > f1 * ratio * margin:
                findings.append(
                    Finding(
                        rule="COST502",
                        severity=SEV_ERROR,
                        location=f"{tag}/{w2}",
                        message=(
                            f"{comp} scales SUPERLINEARLY in the bucket axis: "
                            f"{f1} @ {w1} -> {f2} @ {w2} "
                            f"(x{f2 / f1:.2f} for a x{ratio:.1f} bucket; "
                            f"linear bound {int(f1 * ratio * margin)}) — a "
                            f"decode-phase program grew an O(T^2) term "
                            f"(attention over (W, W) instead of (q, W)?)"
                        ),
                        key=tag,
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# COST503: mixed-step packing efficiency
# ---------------------------------------------------------------------------


def observed_packing(mixed_records: Dict[int, object]) -> Dict:
    """The mixed family's committed packing contract: q tile, slot count,
    and per-bucket ALL-DECODE efficiency — useful tokens (one per active
    row, rows bounded by bucket // q_tile) over compute tokens (the
    bucket). The worst-case steady-state serving step; prefill chunks only
    improve it."""
    any_rec = next(iter(mixed_records.values()))
    meta = any_rec.shape_meta
    eff = {}
    for bucket in sorted(mixed_records):
        active = min(meta.rows, bucket // max(1, meta.q_tile))
        eff[str(bucket)] = round(active / bucket, 6)
    return {"q_tile": meta.q_tile, "num_rows": meta.rows, "efficiency": eff}


def packing_findings(observed: Dict, expected: Optional[Dict]) -> List[Finding]:
    """COST503 comparator (standalone for the proven-detector test)."""
    tag = programs.TAG_MIXED_STEP
    if not expected:
        return [
            Finding(
                rule="COST503",
                severity=SEV_ERROR,
                location=tag,
                message=(
                    "no committed mixed-step packing contract in "
                    "cost_baseline.json — run --write-baseline and review"
                ),
                key=tag,
            )
        ]
    findings: List[Finding] = []
    for field in ("q_tile", "num_rows"):
        if observed.get(field) != expected.get(field):
            findings.append(
                Finding(
                    rule="COST503",
                    severity=SEV_ERROR,
                    location=tag,
                    message=(
                        f"mixed-step packing contract drifted: {field} "
                        f"{expected.get(field)} -> {observed.get(field)} — a "
                        f"packing-granule change moves the padded-token "
                        f"fraction of every serving step; regenerate the "
                        f"baseline only after reviewing the efficiency table"
                    ),
                    key=tag,
                )
            )
    exp_eff = expected.get("efficiency", {})
    for bucket, eff in observed.get("efficiency", {}).items():
        exp = exp_eff.get(bucket)
        if exp is None:
            findings.append(
                Finding(
                    rule="COST503",
                    severity=SEV_ERROR,
                    location=f"{tag}/{bucket}",
                    message=(
                        f"no committed all-decode efficiency for mixed bucket "
                        f"{bucket} — the bucket ladder changed; regenerate "
                        f"and review"
                    ),
                    key=tag,
                )
            )
        elif eff < exp - 1e-9:
            findings.append(
                Finding(
                    rule="COST503",
                    severity=SEV_ERROR,
                    location=f"{tag}/{bucket}",
                    message=(
                        f"mixed-step all-decode efficiency REGRESSED at "
                        f"bucket {bucket}: {exp} -> {eff} (useful/compute "
                        f"tokens) — more of every serving dispatch is "
                        f"padding; review before re-pinning"
                    ),
                    key=tag,
                )
            )
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_cost_baseline(path: Optional[pathlib.Path] = None) -> Dict:
    p = path or BASELINE_PATH
    try:
        with open(p) as f:
            return json.load(f)
    except FileNotFoundError:
        return {}


def save_cost_baseline(data: Dict, path: Optional[pathlib.Path] = None):
    p = path or BASELINE_PATH
    with open(p, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def _baseline_row(census: Dict) -> Dict:
    row = {comp: census[comp] for comp in _COMPONENTS}
    row["classification"] = census["classification"]
    return row


def last_report() -> Dict:
    """Per-bucket cost breakdown of the most recent :func:`run` (the CLI's
    ``"cost"`` JSON section / text table)."""
    return dict(_LAST_REPORT)


def render_breakdown(report: Optional[Dict] = None) -> str:
    """Human-readable per-(tag, bucket) cost + projection table."""
    report = report if report is not None else last_report()
    progs = report.get("programs") if report else None
    if not progs:
        return ""
    lines = [
        "per-(phase, bucket) static cost model "
        "(per-device bytes; projection vs "
        f"{device_model.DEFAULT_DEVICE} nameplate):",
        f"  {'program':<28} {'bucket':>6} {'MFLOPs':>8} {'hbm_KB':>8} "
        f"{'coll_KB':>8} {'bound':>10} {'t_lb_us':>8}",
    ]
    for tag in sorted(progs):
        for bucket in sorted(progs[tag], key=int):
            row = progs[tag][bucket]
            lines.append(
                f"  {tag:<28} {bucket:>6} "
                f"{row['flops'] / 1e6:>8.2f} {row['hbm_bytes'] / 1e3:>8.1f} "
                f"{row['collective_bytes'] / 1e3:>8.1f} "
                f"{row['classification']:>10} "
                f"{row['projection']['t_step_lb_us']:>8.1f}"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def run(
    write_baseline: bool = False,
    baseline_path: Optional[pathlib.Path] = None,
    tags: Tuple[str, ...] = COST_AUDIT_TAGS,
    tolerance_pct: Optional[float] = None,
) -> List[Finding]:
    """Run the cost audit over the requested tags; return findings."""
    global _LAST_REPORT
    findings: List[Finding] = []
    results = programs.collect_programs(tuple(tags))
    baseline = load_cost_baseline(baseline_path)
    tol = (
        tolerance_pct
        if tolerance_pct is not None
        else float(baseline.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))
    )
    base_programs = baseline.get("programs", {})
    observed: Dict[str, Dict[str, Dict]] = {}

    for tag, per_bucket in results.items():
        observed[tag] = {}
        for bucket in sorted(per_bucket):
            rec = per_bucket[bucket]
            census = cost_census(rec)
            observed[tag][str(bucket)] = census
            # -- validity cross-check vs XLA's own accounting --------------
            args_bytes = census["hlo_argument_bytes"]
            resident = census["weights_bytes"] + census["cache_resident_bytes"]
            if args_bytes is not None and resident > args_bytes * 1.05:
                findings.append(
                    Finding(
                        rule="COST501",
                        severity=SEV_ERROR,
                        location=f"{tag}/{bucket}",
                        message=(
                            f"cost model claims {resident} resident "
                            f"weight+cache bytes but the compiled "
                            f"executable's memory_analysis puts ALL "
                            f"arguments at {args_bytes} — the byte model "
                            f"diverged from the program it describes"
                        ),
                        key=tag,
                    )
                )
            if write_baseline:
                continue
            # -- COST501 census gate ---------------------------------------
            expected = base_programs.get(tag, {}).get(str(bucket))
            if expected is None:
                findings.append(
                    Finding(
                        rule="COST501",
                        severity=SEV_ERROR,
                        location=f"{tag}/{bucket}",
                        message=(
                            f"no committed cost census for ({tag}, {bucket}) "
                            f"— run --write-baseline and review/commit "
                            f"cost_baseline.json"
                        ),
                        key=tag,
                    )
                )
            else:
                for comp in _COMPONENTS:
                    old = int(expected.get(comp, 0))
                    new = int(census[comp])
                    if old == new:
                        continue
                    pct = abs(new - old) / max(old, 1) * 100.0
                    if pct <= tol:
                        continue
                    direction = "grew" if new > old else "shrank"
                    findings.append(
                        Finding(
                            rule="COST501",
                            severity=SEV_ERROR,
                            location=f"{tag}/{bucket}",
                            message=(
                                f"cost census {comp} {direction} {pct:.1f}% "
                                f"vs baseline ({old} -> {new}, tolerance "
                                f"{tol}%) — an intentional cost change must "
                                f"regenerate cost_baseline.json "
                                f"(--write-baseline) with the diff reviewed; "
                                f"an unintentional one is the compute/"
                                f"bandwidth regression this gate exists for"
                            ),
                            key=tag,
                        )
                    )
                # -- COST504 regime pin ------------------------------------
                exp_class = expected.get("classification")
                if exp_class and exp_class != census["classification"]:
                    findings.append(
                        Finding(
                            rule="COST504",
                            severity=SEV_ERROR,
                            location=f"{tag}/{bucket}",
                            message=(
                                f"arithmetic-intensity regime FLIPPED: "
                                f"{exp_class} -> {census['classification']} "
                                f"({census['intensity_flops_per_byte']} "
                                f"FLOP/byte vs ridge "
                                f"{device_model.get_device().ridge_flops_per_byte:.0f}) "
                                f"— a dequant/layout change moved this "
                                f"program across the roofline; review and "
                                f"regenerate the baseline if intentional"
                            ),
                            key=tag,
                        )
                    )
        # -- COST502 bucket scaling (decode-phase families) ----------------
        any_rec = next(iter(per_bucket.values()))
        if any_rec.phase != programs.PHASE_CTE and len(per_bucket) >= 2:
            findings.extend(
                scaling_findings(
                    tag, {b: observed[tag][str(b)] for b in per_bucket}
                )
            )

    # -- COST503 mixed packing ---------------------------------------------
    packing = None
    if programs.TAG_MIXED_STEP in results:
        packing = observed_packing(results[programs.TAG_MIXED_STEP])
        if not write_baseline:
            findings.extend(
                packing_findings(packing, baseline.get("mixed_packing"))
            )

    _LAST_REPORT = {"programs": observed}
    if packing is not None:
        _LAST_REPORT["mixed_packing"] = packing

    if write_baseline:
        merged = dict(load_cost_baseline(baseline_path))
        merged.setdefault("programs", {})
        for tag, per_bucket in observed.items():
            merged["programs"][tag] = {
                b: _baseline_row(c) for b, c in per_bucket.items()
            }
        if packing is not None:
            merged["mixed_packing"] = packing
        merged["tolerance_pct"] = tol
        merged["device"] = device_model.DEFAULT_DEVICE
        save_cost_baseline(merged, baseline_path)
    return findings
