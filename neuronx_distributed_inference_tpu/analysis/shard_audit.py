"""Sharding-contract auditor: the realized GSPMD placement of every
committed program must match the PartitionSpec contract the builder/mesh
declare.

GSPMD makes the two most expensive sharding bugs SILENT: a tensor-parallel
weight that loads replicated costs tp_degree× its HBM budget and still
computes the right numbers; a weight-materializing all-gather inside the
decode loop body turns a memory bug into a per-token latency bug. Both are
fully decidable from the partitioned executable we already produce on CPU
(:mod:`.programs`), checked against the machine-readable declarations
(``builder.param_pspecs()`` / ``builder.cache_pspecs()`` via
``TpuModelForCausalLM.declared_pspecs()``):

- **GRAPH301 weight-sharding-mismatch** — every weight leaf's REALIZED input
  sharding in the compiled executable must be equivalent to the declared
  PartitionSpec: no silently replicated tp-sharded weights, no unexpectedly
  sharded replicated leaves (norms, rope tables, the deepseek MLA scale
  leaves — whose replication is declared, not special-cased).
- **GRAPH302 cache-sharding** — no cache leaf may diverge from the declared
  cache spec, no cache-sized (data) leaf may be fully replicated on a >1
  model-parallel mesh, and the step OUTPUT's cache sharding must equal its
  input sharding (a per-step cache reshard would defeat donation).
- **GRAPH303 reshard-in-loop** — no weight-sized all-gather inside the
  decode step's while body (the collective census counts collectives; this
  rule adds POSITION: a gather that runs once at entry is setup cost, the
  same gather inside the loop body re-materializes a weight every token).
- **GRAPH304 sharding census** — the per-program {leaf-path: spec} census
  (params + cache + mesh axis sizes) is pinned to
  ``analysis/shard_baseline.json`` and must not drift without an explicit
  ``--write-baseline`` regeneration; realized shardings must also be
  identical across buckets of one tag.
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import Dict, List, Optional, Set, Tuple

from neuronx_distributed_inference_tpu.analysis import programs
from neuronx_distributed_inference_tpu.analysis.findings import (
    Finding,
    SEV_ERROR,
)

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "shard_baseline.json"

SHARD_AUDIT_TAGS = programs.COMMITTED_TAGS

#: floor for the GRAPH303 weight-sized threshold, so a degenerate tiny model
#: can never classify activation-sized gathers as weights
MIN_WEIGHT_BYTES = 1024

_HLO_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8e3m4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


# ---------------------------------------------------------------------------
# pytree helpers
# ---------------------------------------------------------------------------


def _flatten_contract(declared, realized, values):
    """Zip (leaf path, declared PartitionSpec, realized sharding, value) —
    PartitionSpec subclasses tuple, so the declared tree flattens with an
    explicit is_leaf. Returns None on tree-structure mismatch (itself a
    finding at the call site)."""
    import jax.tree_util as jtu
    from jax.sharding import PartitionSpec as P

    decl = jtu.tree_flatten_with_path(
        declared, is_leaf=lambda x: x is None or isinstance(x, P)
    )[0]
    reals = jtu.tree_leaves(realized)
    vals = jtu.tree_leaves(values)
    if not (len(decl) == len(reals) == len(vals)):
        return None
    return [
        (programs.path_str(path), spec, real, val)
        for (path, spec), real, val in zip(decl, reals, vals)
    ]


def _expected(mesh, spec):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, spec if spec is not None else P())


def _model_group_size(mesh) -> int:
    """Devices a single batch row's model state spans (every axis but the
    whole-model data-parallel one)."""
    size = 1
    for name, n in zip(mesh.axis_names, mesh.devices.shape):
        if name != "ddp":
            size *= n
    return size


def _spec_str(sharding) -> str:
    from neuronx_distributed_inference_tpu.parallel.mesh import sharding_str

    return sharding_str(sharding)


# ---------------------------------------------------------------------------
# GRAPH303: in-loop weight gathers
# ---------------------------------------------------------------------------


def _computations(hlo_text: str) -> Dict[str, List[str]]:
    """Map computation name -> its body lines in a compiled HLO module."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    for line in hlo_text.splitlines():
        if line and not line.startswith(" ") and line.rstrip().endswith("{") \
                and not line.startswith("HloModule"):
            head = line.strip()
            if head.startswith("ENTRY"):
                head = head[len("ENTRY"):].strip()
            cur = head.split("(", 1)[0].strip().lstrip("%").strip()
            comps[cur] = []
        elif cur is not None:
            if line.strip() == "}":
                cur = None
            else:
                comps[cur].append(line)
    return comps


_CALLEE_RE = re.compile(
    r"(?:calls|to_apply|body|condition|branch_computations=\{)=?%?([\w.\-]+)"
)


def _loop_reachable(comps: Dict[str, List[str]]) -> Set[str]:
    """Computation names reachable from any while-loop BODY (transitively
    through calls/fusions) — "inside the decode step loop" for GRAPH303."""
    bodies: Set[str] = set()
    for lines in comps.values():
        for line in lines:
            m = re.search(r"body=%?([\w.\-]+)", line)
            if m:
                bodies.add(m.group(1))
    seen: Set[str] = set()
    frontier = [b for b in bodies if b in comps]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        for line in comps.get(name, ()):
            for m in _CALLEE_RE.finditer(line):
                callee = m.group(1)
                if callee in comps and callee not in seen:
                    frontier.append(callee)
    return seen


def _max_buffer_bytes(line: str) -> int:
    """Largest typed buffer mentioned on an HLO line (result or operand)."""
    best = 0
    for m in re.finditer(r"(\w+)\[([0-9,]*)\]", line):
        dtype, dims = m.group(1), m.group(2)
        nbytes = _HLO_DTYPE_BYTES.get(dtype)
        if nbytes is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n * nbytes)
    return best


def _strip_lead_ones(dims: Tuple[int, ...]) -> Tuple[int, ...]:
    """Drop leading unit dims: a scan body gathers the per-layer slice as
    `[1, ...]` before the reshape squeezes it."""
    i = 0
    while i < len(dims) - 1 and dims[i] == 1:
        i += 1
    return dims[i:]


def _result_buffer(line: str) -> Optional[Tuple[str, Tuple[int, ...]]]:
    """(hlo dtype, dims) of the op's result: the typed buffer right after
    `=` (`%name = f32[2,128]{...} op(...)`); for a tuple result (async
    all-gather-start carries (operand, gathered)), the largest element."""
    m = re.search(r"=\s+(\([^)]*\)|\w+\[[0-9,]*\])", line)
    if not m:
        return None
    best: Optional[Tuple[str, Tuple[int, ...]]] = None
    best_bytes = -1
    for bm in re.finditer(r"(\w+)\[([0-9,]*)\]", m.group(1)):
        dtype, dims_s = bm.group(1), bm.group(2)
        dims = tuple(int(d) for d in dims_s.split(",") if d)
        nbytes = _HLO_DTYPE_BYTES.get(dtype, 0)
        for d in dims:
            nbytes *= d
        if nbytes > best_bytes:
            best, best_bytes = (dtype, dims), nbytes
    return best


def in_loop_gather_findings(
    hlo_text: str,
    min_bytes: int,
    location: str,
    key: str,
    weight_sigs: Optional[Set[Tuple[str, Tuple[int, ...]]]] = None,
) -> List[Finding]:
    """GRAPH303 detector over one compiled module's text: weight-sized
    all-gathers inside while-body-reachable computations. Exposed standalone
    so the proven-detector test can feed it a deliberately broken program.

    ``weight_sigs`` — when given, the (dtype, dims) signatures of the
    program's tp-sharded weight leaves (stacked ``layers/`` leaves both
    whole and with L divided out): a gather is only weight-MATERIALIZING if
    its result buffer exactly matches one. Size alone cannot separate
    weights from activations once per-layer resharding is the declared
    convention (grouped-int4 shards output-only, so decode activations
    legitimately re-gather each step and scale with the token bucket)."""
    findings: List[Finding] = []
    comps = _computations(hlo_text)
    in_loop = _loop_reachable(comps)
    sigs_norm = (
        {(d, _strip_lead_ones(s)) for d, s in weight_sigs}
        if weight_sigs is not None
        else None
    )
    for name in sorted(in_loop):
        for line in comps[name]:
            if "all-gather(" not in line and "all-gather-start(" not in line:
                continue
            nbytes = _max_buffer_bytes(line)
            if nbytes < min_bytes:
                continue
            if sigs_norm is not None:
                buf = _result_buffer(line)
                if buf is None:
                    continue
                if (buf[0], _strip_lead_ones(buf[1])) not in sigs_norm:
                    continue
            findings.append(
                Finding(
                    rule="GRAPH303",
                    severity=SEV_ERROR,
                    location=location,
                    message=(
                        f"weight-materializing all-gather ({nbytes} bytes ≥ "
                        f"threshold {min_bytes}) INSIDE the step's loop body "
                        f"(computation {name}) — a weight is re-gathered "
                        f"every iteration; hoist the reshard out of the loop "
                        f"or fix the constraint that forces it: "
                        f"{line.strip()[:120]}"
                    ),
                    key=key,
                )
            )
    return findings


_NP_TO_HLO_DTYPE = {
    "bool": "pred",
    "int8": "s8", "uint8": "u8",
    "int16": "s16", "uint16": "u16", "float16": "f16", "bfloat16": "bf16",
    "int32": "s32", "uint32": "u32", "float32": "f32",
    "int64": "s64", "uint64": "u64", "float64": "f64",
}


def weight_gather_signatures(rec) -> Set[Tuple[str, Tuple[int, ...]]]:
    """(hlo dtype, dims) signatures of the program's tp-sharded weight
    leaves, for the GRAPH303 weight-vs-activation discrimination. Stacked
    ``layers/...`` leaves contribute both the whole stack and the per-layer
    slice (an unrolled loop gathers the slice; a pathological one the
    stack). 1-d leaves (biases/norms) are excluded — too collision-prone
    with activation shapes."""
    contract = _flatten_contract(
        rec.declared_param_pspecs, rec.realized_param_shardings, rec.params
    )
    sigs: Set[Tuple[str, Tuple[int, ...]]] = set()
    for path, spec, _real, leaf in contract or ():
        if spec is None or not any(e is not None for e in spec):
            continue
        dtype = _NP_TO_HLO_DTYPE.get(str(leaf.dtype))
        if dtype is None or leaf.ndim < 2:
            continue
        sigs.add((dtype, tuple(int(d) for d in leaf.shape)))
        if "layers" in path.split("/") and leaf.ndim >= 3:
            sigs.add((dtype, tuple(int(d) for d in leaf.shape[1:])))
    return sigs


def weight_gather_threshold(rec) -> int:
    """Weight-sized byte threshold for GRAPH303: the smallest per-layer full
    size among the program's tensor-parallel-declared weight leaves (stacked
    ``layers/...`` leaves divide out their leading L). Anything the loop
    body gathers at or above this size is weight-shaped, not an
    activation."""
    contract = _flatten_contract(
        rec.declared_param_pspecs, rec.realized_param_shardings, rec.params
    )
    best: Optional[int] = None
    for path, spec, _real, leaf in contract or ():
        if spec is None or not any(e is not None for e in spec):
            continue  # replicated leaf: not a tp-sharded weight
        nbytes = int(leaf.nbytes)
        if "layers" in path.split("/"):
            nbytes //= max(1, int(leaf.shape[0]))
        best = nbytes if best is None else min(best, nbytes)
    return max(MIN_WEIGHT_BYTES, best or MIN_WEIGHT_BYTES)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_shard_baseline(path: Optional[pathlib.Path] = None) -> Dict:
    p = path or BASELINE_PATH
    try:
        with open(p) as f:
            return json.load(f).get("census", {})
    except FileNotFoundError:
        return {}


def save_shard_baseline(census: Dict, path: Optional[pathlib.Path] = None):
    p = path or BASELINE_PATH
    with open(p, "w") as f:
        json.dump({"census": census}, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def _audit_leaves(
    tag: str,
    bucket: int,
    rule: str,
    kind: str,
    declared,
    realized,
    values,
    mesh,
    findings: List[Finding],
) -> Dict[str, str]:
    """Shared GRAPH301/302 per-leaf walk. Returns the {path: spec} census
    fragment for the realized shardings."""
    contract = _flatten_contract(declared, realized, values)
    if contract is None:
        findings.append(
            Finding(
                rule=rule,
                severity=SEV_ERROR,
                location=f"{tag}/{bucket}",
                message=(
                    f"declared {kind} PartitionSpec tree does not match the "
                    f"committed {kind} tree structure — the declaration "
                    f"drifted from what load() actually shards"
                ),
                key=tag,
            )
        )
        return {}
    census: Dict[str, str] = {}
    for path, spec, real, leaf in contract:
        census[path] = _spec_str(real)
        exp = _expected(mesh, spec)
        if real.is_equivalent_to(exp, leaf.ndim):
            continue
        declared_sharded = spec is not None and any(e is not None for e in spec)
        if declared_sharded and real.is_fully_replicated:
            detail = (
                f"declared tp-sharded but realized FULLY REPLICATED — this "
                f"leaf costs {_model_group_size(mesh)}x its budgeted HBM"
            )
        elif not declared_sharded and not real.is_fully_replicated:
            detail = "declared replicated but realized sharded"
        else:
            detail = "realized sharding diverges from the declaration"
        findings.append(
            Finding(
                rule=rule,
                severity=SEV_ERROR,
                location=f"{tag}/{bucket}",
                message=(
                    f"{kind} leaf {path}: {detail} (declared "
                    f"{_spec_str(_expected(mesh, spec))}, realized "
                    f"{_spec_str(real)})"
                ),
                key=tag,
            )
        )
    return census


def cache_replication_findings(
    declared, realized, values, mesh, location: str, key: str
) -> List[Finding]:
    """GRAPH302 catastrophic-replication check: no cache-sized (data) leaf
    may be fully replicated on a >1 model-parallel mesh — replication
    multiplies the largest tensor in the system by the group size. Scale
    leaves ((L, H) floats) are audited by the declared-spec walk; the size
    gate keeps them out of this check. A leaf whose DECLARED spec is
    replicated is exempt: that replication is the builder's explicit
    contract (the deepseek MLA latent streams), already audited by the
    declared-spec walk — this check is for replication nobody asked for.
    Standalone so the proven-detector test can feed it a deliberately
    replicated cache."""
    group = _model_group_size(mesh)
    if group <= 1:
        return []
    findings: List[Finding] = []
    cache_leaves = _flatten_contract(declared, realized, values)
    data_bytes = [int(leaf.nbytes) for _, _, _, leaf in cache_leaves or ()]
    big = max(data_bytes, default=0) // 4  # data leaves dwarf scales
    for path, spec, real, leaf in cache_leaves or ():
        declared_replicated = spec is None or not any(
            e is not None for e in spec
        )
        if declared_replicated:
            continue
        if int(leaf.nbytes) >= max(big, 1) and real.is_fully_replicated:
            findings.append(
                Finding(
                    rule="GRAPH302",
                    severity=SEV_ERROR,
                    location=location,
                    message=(
                        f"cache leaf {path} ({int(leaf.nbytes)} bytes) "
                        f"is FULLY REPLICATED across the {group}-device "
                        f"model group — the cache is the largest tensor "
                        f"in the system; it must shard"
                    ),
                    key=key,
                )
            )
    return findings


def run(
    write_baseline: bool = False,
    baseline_path: Optional[pathlib.Path] = None,
    tags: Tuple[str, ...] = SHARD_AUDIT_TAGS,
) -> List[Finding]:
    """Run the shard audit over the requested tags; return findings."""
    import jax.tree_util as jtu

    from neuronx_distributed_inference_tpu.parallel.mesh import mesh_axis_sizes

    findings: List[Finding] = []
    results = programs.collect_programs(tuple(tags))
    baseline = load_shard_baseline(baseline_path)
    observed: Dict[str, Dict] = {}

    for tag, per_bucket in results.items():
        buckets = sorted(per_bucket)
        ref_bucket = buckets[0]
        ref = per_bucket[ref_bucket]

        # GRAPH301/302 leaf walks run on EVERY bucket (no extra tracing —
        # the shardings are already on the compiled records), so a placement
        # that diverges only at a larger bucket still surfaces at its own
        # location
        param_censuses: Dict[int, Dict[str, str]] = {}
        cache_censuses: Dict[int, Dict[str, str]] = {}
        for b in buckets:
            rec = per_bucket[b]
            param_censuses[b] = _audit_leaves(
                tag, b, "GRAPH301", "weight",
                rec.declared_param_pspecs, rec.realized_param_shardings,
                rec.params, rec.mesh, findings,
            )
            cache_censuses[b] = _audit_leaves(
                tag, b, "GRAPH302", "cache",
                rec.declared_cache_pspecs, rec.realized_cache_shardings,
                rec.cache, rec.mesh, findings,
            )
            findings.extend(
                cache_replication_findings(
                    rec.declared_cache_pspecs, rec.realized_cache_shardings,
                    rec.cache, rec.mesh, f"{tag}/{b}", tag,
                )
            )
            # GRAPH302: the step output must hand the cache back in the SAME
            # sharding it came in with (donation aliases the buffers; a
            # reshard would force a copy every step)
            if rec.output_cache_shardings is None:
                continue
            in_flat = jtu.tree_leaves(rec.realized_cache_shardings)
            out_flat = jtu.tree_leaves(rec.output_cache_shardings)
            if len(in_flat) != len(out_flat):
                continue
            cache_paths = [
                p for p, *_ in _flatten_contract(
                    rec.declared_cache_pspecs,
                    rec.realized_cache_shardings,
                    rec.cache,
                ) or ()
            ]
            for path, s_in, s_out, leaf in zip(
                cache_paths, in_flat, out_flat, jtu.tree_leaves(rec.cache)
            ):
                if not s_out.is_equivalent_to(s_in, leaf.ndim):
                    findings.append(
                        Finding(
                            rule="GRAPH302",
                            severity=SEV_ERROR,
                            location=f"{tag}/{b}",
                            message=(
                                f"cache leaf {path} changes sharding "
                                f"across the step ({_spec_str(s_in)} in, "
                                f"{_spec_str(s_out)} out) — donation "
                                f"cannot alias a resharded buffer"
                            ),
                            key=tag,
                        )
                    )
        param_census = param_censuses[ref_bucket]
        cache_census = cache_censuses[ref_bucket]

        # GRAPH303: decode-phase programs must not re-gather weights in-loop
        if ref.phase == programs.PHASE_TKG:
            threshold = weight_gather_threshold(ref)
            sigs = weight_gather_signatures(ref)
            for b in buckets:
                findings.extend(
                    in_loop_gather_findings(
                        per_bucket[b].compiled_text, threshold,
                        f"{tag}/{b}", tag, weight_sigs=sigs,
                    )
                )

        # GRAPH304: sharding census — identical across buckets, pinned to
        # the committed baseline
        tag_census = {
            "mesh": {k: int(v) for k, v in mesh_axis_sizes(ref.mesh).items()},
            "params": param_census,
            "cache": cache_census,
        }
        for b in buckets[1:]:
            if (
                param_censuses[b] != param_census
                or cache_censuses[b] != cache_census
            ):
                which = (
                    "weight" if param_censuses[b] != param_census else "cache"
                )
                findings.append(
                    Finding(
                        rule="GRAPH304",
                        severity=SEV_ERROR,
                        location=f"{tag}/{b}",
                        message=(
                            f"realized {which} shardings differ between "
                            f"buckets {ref_bucket} and {b} — buckets must "
                            f"share one placement"
                        ),
                        key=tag,
                    )
                )
        observed[tag] = tag_census
        expected = None if write_baseline else baseline.get(tag)
        if expected is not None and expected != tag_census:
            drift = sorted(
                k
                for section in ("params", "cache")
                for k in (
                    set(expected.get(section, {})) | set(tag_census[section])
                )
                if expected.get(section, {}).get(k)
                != tag_census[section].get(k)
            ) or ["mesh"]
            findings.append(
                Finding(
                    rule="GRAPH304",
                    severity=SEV_ERROR,
                    location=f"{tag}/{ref_bucket}",
                    message=(
                        f"sharding census drifted from shard_baseline.json "
                        f"(changed leaves: {drift[:6]}"
                        f"{'...' if len(drift) > 6 else ''}) — regenerate "
                        f"with --write-baseline only for an intentional "
                        f"placement change and review the diff"
                    ),
                    key=tag,
                )
            )
        elif expected is None and not write_baseline:
            findings.append(
                Finding(
                    rule="GRAPH304",
                    severity=SEV_ERROR,
                    location=f"{tag}/{ref_bucket}",
                    message=(
                        f"no committed sharding census for tag {tag} — run "
                        f"--write-baseline and review/commit "
                        f"shard_baseline.json"
                    ),
                    key=tag,
                )
            )

    if write_baseline:
        merged = dict(load_shard_baseline(baseline_path))
        merged.update(observed)
        save_shard_baseline(merged, baseline_path)
    return findings
