"""Configuration system.

TPU-native re-design of the reference config stack
(reference: src/neuronx_distributed_inference/models/config.py:81-1064):

- :class:`TpuConfig` — the flat runtime/feature config (reference ``NeuronConfig``,
  config.py:81-652). Every serving feature is a field here; validation of feature
  interactions happens in ``__post_init__`` (reference scatters it through
  ``NeuronConfig.__init__``).
- :class:`InferenceConfig` — wraps a ``TpuConfig`` plus the HF model attributes,
  with ``attribute_map`` aliasing and JSON round-trip
  (reference config.py:716-909).
- Sub-configs: :class:`OnDeviceSamplingConfig` (config.py:931),
  :class:`FusedSpecConfig` (config.py:912), :class:`ChunkedPrefillConfig`
  (config.py:944), :class:`MoETpuConfig` (config.py:665-713),
  :class:`LoraServingConfig` (modules/lora_serving/config.py).

Differences by design (TPU-first):
- dtypes are jnp dtypes serialized as strings.
- Parallel degrees map onto named ``jax.sharding.Mesh`` axes instead of process
  groups; ``world_size`` is derived identically (config.py:353-355).
- No compiler-flag strings: XLA options are set via jit/compilation-cache.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp

# ---------------------------------------------------------------------------
# dtype handling
# ---------------------------------------------------------------------------

def to_dtype(name_or_dtype) -> Any:
    """Resolve a dtype name (or dtype) to a jnp dtype."""
    if isinstance(name_or_dtype, str):
        key = name_or_dtype.replace("torch.", "")
        table = {
            "float32": jnp.float32,
            "fp32": jnp.float32,
            "bfloat16": jnp.bfloat16,
            "bf16": jnp.bfloat16,
            "float16": jnp.float16,
            "fp16": jnp.float16,
            "int8": jnp.int8,
            "fp8": jnp.float8_e4m3fn,
            "float8_e4m3": jnp.float8_e4m3fn,
            "float8_e5m2": jnp.float8_e5m2,
        }
        if key not in table:
            raise ValueError(f"Unknown dtype name: {name_or_dtype}")
        return table[key]
    return name_or_dtype


def dtype_name(dtype) -> str:
    return jnp.dtype(dtype).name


#: accepted kv_cache_dtype names: plain storage dtypes plus the quantized
#: (codes + per-(layer, head) scales) cache formats. Anything else fails
#: validation loudly — an unknown string must not silently serve bf16.
KV_CACHE_DTYPES = (
    "bfloat16", "bf16", "float16", "fp16", "float32", "fp32",
    "int8", "fp8", "float8_e4m3", "float8_e5m2",
)
KV_QUANT_DTYPE_NAMES = ("int8", "fp8", "float8_e4m3", "float8_e5m2")

#: multi-replica router placement policies (runtime/router.py consumes this
#: as its PLACEMENT_POLICIES registry — defined here so config validation
#: needs no runtime import)
ROUTER_POLICIES = ("round_robin", "least_loaded", "cache_aware")


# ---------------------------------------------------------------------------
# Sub-configs
# ---------------------------------------------------------------------------


@dataclass
class OnDeviceSamplingConfig:
    """On-device sampler settings (reference config.py:931-941)."""

    do_sample: bool = False
    top_k: int = 1
    top_p: float = 1.0
    temperature: float = 1.0
    dynamic: bool = True  # per-request (top_k, top_p, temperature) tensor
    global_topk: int = 256  # stage-1 topk width for distributed sampling
    deterministic: bool = False
    on_device_sampling: bool = True

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**_strict_kwargs(cls, dict(d)))


@dataclass
class FusedSpecConfig:
    """Fused speculation: draft + target compiled into one graph
    (reference config.py:912-928, model_base.py:1656)."""

    draft_model_name: str = ""
    draft_config: Optional["InferenceConfig"] = None
    worker_cls_name: str = ""

    def to_dict(self):
        d = {"draft_model_name": self.draft_model_name, "worker_cls_name": self.worker_cls_name}
        if self.draft_config is not None:
            d["draft_config"] = self.draft_config.to_dict()
        return d

    @classmethod
    def from_dict(cls, d):
        draft = d.get("draft_config")
        return cls(
            draft_model_name=d.get("draft_model_name", ""),
            draft_config=InferenceConfig.from_dict(draft) if draft else None,
            worker_cls_name=d.get("worker_cls_name", ""),
        )


@dataclass
class ChunkedPrefillConfig:
    """Chunked prefill settings (reference config.py:944-959)."""

    max_num_seqs: int = 8
    tkg_model_enabled: bool = True
    kernel_q_tile_size: int = 128
    kernel_kv_tile_size: int = 512

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**_strict_kwargs(cls, dict(d)))


@dataclass
class _TapPointsConfig:
    """Shared base: a validated list of tensor-tap point names
    (modules/tensor_taps.TAP_POINTS)."""

    points: List[str] = field(default_factory=list)

    def __post_init__(self):
        from neuronx_distributed_inference_tpu.modules.tensor_taps import TAP_POINTS

        unknown = set(self.points) - set(TAP_POINTS)
        if unknown:
            raise ValueError(
                f"unknown tap point(s) {sorted(unknown)} for "
                f"{type(self).__name__}; available: {TAP_POINTS}"
            )

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(**_strict_kwargs(cls, dict(d)))


@dataclass
class TensorCaptureConfig(_TapPointsConfig):
    """Capture named intermediate tensors from the traced forward
    (reference TensorCaptureConfig, config.py:987; capture plumbing
    model_base.py:1120-1226)."""


@dataclass
class TensorReplacementConfig(_TapPointsConfig):
    """Teacher-force named intermediate tensors with host-provided goldens
    (reference TensorReplacementConfig, config.py:1038 +
    utils/tensor_replacement/registry.py). The golden arrays are supplied
    per call (application.capture_forward replacements=...)."""


@dataclass
class LoraServingConfig:
    """Multi-adapter LoRA serving (reference modules/lora_serving/config.py)."""

    max_loras: int = 1
    max_lora_rank: int = 16
    max_loras_on_cpu: int = 2
    lora_ckpt_paths: Optional[Dict[str, str]] = None
    lora_dtype: str = "bfloat16"
    target_modules: Tuple[str, ...] = ("q_proj", "k_proj", "v_proj", "o_proj")

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["target_modules"] = list(self.target_modules)
        return d

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        if "target_modules" in d:
            d["target_modules"] = tuple(d["target_modules"])
        return cls(**_strict_kwargs(cls, d))


def _field_names(cls) -> set:
    return {f.name for f in dataclasses.fields(cls)}


def _strict_kwargs(cls, d: dict) -> dict:
    """Reject unknown keys when deserializing a config.

    A typo'd feature flag in a saved ``tpu_config.json`` must fail loudly, not
    round-trip to silently-off (the same contract the in-memory
    ``UNIMPLEMENTED_FLAGS`` audit enforces for live configs).
    """
    unknown = sorted(set(d) - _field_names(cls))
    if unknown:
        raise ValueError(
            f"Unknown {cls.__name__} key(s) in serialized config: {unknown}. "
            "Refusing to silently drop them — this usually means the artifact "
            "was saved by a DIFFERENT framework version. Migration: re-save "
            "the compiled artifact with this version (compile() writes a "
            "fresh tpu_config.json), or delete the stale key(s) from "
            "tpu_config.json if their features are no longer configured."
        )
    return d


# ---------------------------------------------------------------------------
# Reference-parity flags that are NOT implemented yet. Setting one raises
# NotImplementedError instead of silently no-oping (VERDICT r1 weak #4: an
# accepted-but-ignored feature flag inflates apparent parity). Entries are
# removed as the features land; tests/test_flag_audit.py keys off this table.
# field -> (inert default, short reason)
# ---------------------------------------------------------------------------

UNIMPLEMENTED_FLAGS: Dict[str, Tuple[Any, str]] = {
    "is_eagle_target": (
        False,
        "per-submodel role flags are internal to the reference's config "
        "specialization; use runtime/fused_spec.TpuEagleSpecModelForCausalLM",
    ),
    "is_eagle_draft": (
        False,
        "per-submodel role flags are internal to the reference's config "
        "specialization; use runtime/fused_spec.TpuEagleSpecModelForCausalLM",
    ),
    "k_cache_transposed": (
        False,
        "XLA owns cache layouts on TPU; the transposed-K layout knob is a "
        "NKI-kernel detail with no TPU equivalent",
    ),
    "rpl_reduce_dtype": (
        None,
        "GSPMD emits collectives in the tensor dtype; a separate reduce dtype "
        "is not plumbed",
    ),
    "kv_cache_padding_size": (
        0,
        "garbage writes use a spare batch row on TPU (kvcache.py); cache-tail "
        "padding is a NKI detail with no TPU equivalent",
    ),
    "weights_to_skip_layout_optimization": (None, "XLA owns weight layouts on TPU"),
}

# MoETpuConfig-only parity flags, same contract (empty: every MoE flag is
# implemented as of round 4)
UNIMPLEMENTED_MOE_FLAGS: Dict[str, Tuple[Any, str]] = {}


# ---------------------------------------------------------------------------
# TpuConfig (reference NeuronConfig)
# ---------------------------------------------------------------------------


@dataclass
class TpuConfig:
    """Flat runtime/feature config (reference NeuronConfig, config.py:81-652).

    One instance per compiled sub-model; the application deep-copies and
    specializes it per sub-model tag (reference model_base.py:3099-3222).
    """

    # --- core shapes -----------------------------------------------------
    batch_size: int = 1
    max_batch_size: Optional[int] = None  # defaults to batch_size
    ctx_batch_size: Optional[int] = None
    tkg_batch_size: Optional[int] = None
    seq_len: int = 128
    max_context_length: Optional[int] = None  # defaults to seq_len
    n_active_tokens: Optional[int] = None  # tokens processed per step (CTE: bucket len)
    max_new_tokens: Optional[int] = None
    max_length: Optional[int] = None

    # --- dtypes ----------------------------------------------------------
    dtype: str = "bfloat16"  # compute/weight dtype
    rpl_reduce_dtype: Optional[str] = None  # dtype for cross-shard reductions
    cast_logits_fp32: bool = True
    attention_softmax_fp32: bool = True

    # --- bucketing (reference modules/autobucketing.py) ------------------
    enable_bucketing: bool = True
    buckets: Optional[List[int]] = None  # resolved at build
    context_encoding_buckets: Optional[List[int]] = None
    token_generation_buckets: Optional[List[int]] = None

    # --- batching --------------------------------------------------------
    is_continuous_batching: bool = False
    padding_side: str = "right"

    # --- sampling --------------------------------------------------------
    on_device_sampling_config: Optional[OnDeviceSamplingConfig] = None
    max_topk: int = 256
    output_logits: bool = False

    # --- KV cache --------------------------------------------------------
    # None = store in `dtype`; "int8"/"fp8" build the quantized cache
    # (codes + per-(layer, head) running-absmax scales, modules/kvcache.py)
    # with fused in-kernel dequant on the decode/paged kernels. Validated
    # against KV_CACHE_DTYPES — unknown names fail loudly.
    kv_cache_dtype: Optional[str] = None
    is_block_kv_layout: bool = False  # paged KV cache
    pa_num_blocks: Optional[int] = None
    pa_block_size: int = 16
    # size the paged block pool by HBM BYTES instead of a block count: the
    # application derives pa_num_blocks = pa_pool_bytes // true-per-block
    # byte cost in the CACHE dtype (block_kvcache.kv_block_bytes) — a
    # quantized cache admits ~2x the blocks for the same budget
    pa_pool_bytes: Optional[int] = None
    is_prefix_caching: bool = False
    is_chunked_prefill: bool = False
    chunked_prefill_config: Optional[ChunkedPrefillConfig] = None
    kv_cache_batch_size: Optional[int] = None
    kv_cache_padding_size: int = 0
    # ragged mixed-step serving dispatch (runtime/serving.py): pack admitted
    # prefill chunks AND active decode rows into ONE ragged paged-attention
    # dispatch per step() (ops/ragged_paged_attention.py), collapsing the
    # CTE/TKG split on the serving path. Requires the paged cache
    # (is_block_kv_layout) under continuous batching; plain full-length
    # attention only. Default OFF until hardware-validated — the legacy
    # split dispatch stays byte-identical (pinned by test; quantized KV
    # caches agree within the kv-quant tolerance instead: the running
    # absmax couples whatever one dispatch co-writes, and the ragged step
    # groups writes differently — docs/SERVING.md).
    serving_ragged: bool = False
    # async 1-ahead pipelining for the ragged mixed-step path: serving step
    # k+1 chains on step k's still-on-device tokens (device-side chained-id
    # gather, epoch-guarded one-step-late consume) and the step-k fetch is
    # started non-blocking at dispatch — host bookkeeping (admission,
    # deadlines, watchdog, telemetry) overlaps the device executing k+1.
    # None (default) follows async_mode, mirroring the split path's 1-ahead
    # decode; False forces dispatch+fetch-per-step (step-accurate
    # debugging). Greedy outputs are byte-identical across sync/async
    # (pinned). Requires serving_ragged.
    serving_ragged_async: Optional[bool] = None
    # speculative verification INSIDE the ragged mixed step
    # (runtime/serving.SpeculativeServingSession over the mixed_step_spec
    # program family): spec rows carry their draft tokens as extra query
    # positions on the packed axis, one mixed dispatch per step serves
    # prefill chunks + plain decode + spec-verify rows, accept/rollback
    # commits against the paged cache, and draft length adapts per request
    # off the acceptance EWMA. Requires serving_ragged (paged cache +
    # continuous batching) + chunked prefill + 2 <= speculation_length <= 16
    # (a spec segment must fit one RAGGED_Q_TILE); greedy-only (the packed
    # verify computes contiguous-match acceptance on device).
    serving_spec_ragged: bool = False
    # multi-replica serving front-end (runtime/router.py): how many
    # single-chip replica sessions the ServingRouter runs the demo/bench
    # serving traffic over (1 = no router layer), and the placement policy
    # that binds requests to replicas. `least_loaded` scores replicas from
    # live telemetry signals (re-admission backlog, occupancy, kv_free_bytes
    # headroom, EWMAs of step-host/queue-wait ms); `round_robin` cycles the
    # healthy set; `cache_aware` ranks candidates by each replica's REAL
    # prefix-cache match index (longest cached block-chain of the prompt),
    # load order breaking ties.
    serving_replicas: int = 1
    router_policy: str = "least_loaded"
    # disaggregated prefill tier (runtime/router.py + runtime/disaggregated
    # .py): carve this many of `serving_replicas` out as DEDICATED prefill
    # replicas — they run context encoding + extract_request_kv only, and
    # the remaining (serving_replicas - router_prefill_replicas) decode
    # replicas inject the handed-over KV and serve decode. A 16k-prompt
    # burst then never stalls a co-located decode row's ITL. The KV hand-off
    # is a CONTAINED failure domain: payload validation at inject (a corrupt
    # or truncated hand-off terminally fails ONE request with typed
    # FAILED(handoff), destination KV scrubbed), bounded hand-off retry with
    # capped backoff, and tier-wide graceful degradation (every prefill
    # replica dead => decode replicas fall back to local monolithic prefill,
    # loudly — nxdi_handoff_local_prefill_total). Requires the contiguous
    # cache (the hand-off scatters whole cache lines; paged decode caches
    # are not supported) under continuous batching. 0 = no tier (every
    # replica prefills locally). See docs/SERVING.md "Disaggregated prefill
    # tier".
    router_prefill_replicas: int = 0
    # hand-off containment knobs: transient hand-off failures (transit loss,
    # timeout, a transient prefill dispatch error) retry up to
    # handoff_max_retries times with capped backoff — exhaustion terminally
    # fails ONLY the in-flight request (FAILED(handoff)) and degrades the
    # prefill replica like a dispatch give-up. handoff_timeout_s bounds one
    # hand-off attempt's wall clock (None = no timeout; an attempt observed
    # past it counts as a failed attempt and retries).
    handoff_max_retries: int = 2
    handoff_timeout_s: Optional[float] = None
    # thread-per-replica router stepping (runtime/router.py): ServingRouter
    # dispatches every alive replica's step() from a persistent pool of one
    # worker thread per replica and waits on a per-step barrier — dispatch
    # and the non-blocking token fetches release the GIL, so N replicas'
    # device steps overlap instead of host-serializing behind one Python
    # loop. Placement, admission, failover harvesting, terminal sync and
    # every telemetry gauge stay on the router thread; ONLY
    # ReplicaHandle.step() runs on workers — the confinement model the
    # concurrency audit (CONC601-604, analysis/concurrency_audit.py) proves
    # statically. Default OFF until hardware-validated; threaded drains are
    # pinned byte-identical to sequential stepping (tests/
    # test_router_threaded.py). See docs/SERVING.md "Threaded replica
    # stepping".
    router_threading: bool = False

    # --- attention -------------------------------------------------------
    fused_qkv: bool = False
    sliding_window: Optional[int] = None
    attention_chunk_size: Optional[int] = None  # chunked attention (llama4)
    flash_decoding_enabled: bool = False
    num_cores_per_group: int = 1
    attn_kernel_enabled: Optional[bool] = None  # None = auto (pallas flash attn on TPU)
    # head-pair packed flash prefill (ops/flash_attention.py packed path):
    # D<=64 models run attention with two heads per 128-lane tile at full
    # MXU contraction depth. None = auto-on for causal D<=64 shapes
    # whenever the flash kernel runs, True = force (still honors shape
    # guards), False = keep the unpacked kernel. The packed softmax
    # intermediates follow attention_softmax_fp32: the default (True) keeps
    # fp32 exp/PV like the unpacked kernel; set it False to add the bf16
    # VPU/MXU win on top of the packing.
    attn_packed_kernel_enabled: Optional[bool] = None
    # decode (TKG) attention kernel, contiguous + paged (ops/decode_attention.py):
    # None = auto on TPU, True = force, False = native gather path.
    # NOTE: artifacts saved before this feature landed serialized the then-
    # inert default `false`, which now pins the native path — re-save the
    # artifact (or edit tpu_config.json to null) to restore auto.
    attn_block_tkg_kernel_enabled: Optional[bool] = None
    # fused decode-layer Pallas kernels (ops/decode_block.py): the attention
    # BLOCK (rmsnorm+fused-QKV+rope+attention+o-proj, reference
    # attention_block_tokengen_nki_kernel, attention_base.py:1609 — requires
    # fused_qkv) and the gated-MLP block. Tri-state like the other kernels.
    fused_attn_block_kernel_enabled: Optional[bool] = None
    fused_mlp_kernel_enabled: Optional[bool] = None
    k_cache_transposed: bool = False
    qk_norm: bool = False

    # --- speculation -----------------------------------------------------
    speculation_length: int = 0
    enable_fused_speculation: bool = False
    enable_eagle_speculation: bool = False
    enable_eagle_draft_input_norm: bool = False
    # EAGLE3: multi-layer target hidden capture + fused 2H-qkv draft layer
    # (reference is_eagle3, model_base.py:1444-1479)
    is_eagle3: bool = False
    is_eagle_target: bool = False
    is_eagle_draft: bool = False
    medusa_speculation_length: int = 0
    num_medusa_heads: int = 0
    token_tree_config: Optional[dict] = None

    # --- parallelism (mesh axes; reference config.py:333-361) ------------
    tp_degree: int = 1
    cp_degree: int = 1  # context parallel (prefill attention)
    attention_dp_degree: int = 1  # data parallel decode attention
    # whole-model data parallel (leading ddp mesh axis; rides DCN multi-host:
    # weights replicate, the batch shards). TPU-native extension — the
    # reference runs whole-model DP as separate vLLM replicas.
    data_parallel_degree: int = 1
    pp_degree: int = 1
    ep_degree: int = 1
    moe_tp_degree: Optional[int] = None
    moe_ep_degree: Optional[int] = None
    start_rank_id: int = 0
    local_ranks_size: Optional[int] = None
    sequence_parallel_enabled: bool = False
    vocab_parallel: bool = False
    is_prefill_stage: Optional[bool] = None

    # --- quantization ----------------------------------------------------
    quantized: bool = False
    quantization_type: str = "per_channel_symmetric"  # or per_tensor_symmetric, blockwise
    quantization_dtype: str = "int8"
    modules_to_not_convert: Optional[List[str]] = None
    # pre-quantized checkpoint dir: loaded when present, written after the
    # first quantize-at-load (reference quantized_checkpoints_path,
    # application_base.py:636-797)
    quantized_checkpoints_path: Optional[str] = None
    # input-axis block size for quantization_type="blockwise" (reference
    # blockwise_matmul_block_size, config.py:665-713)
    blockwise_matmul_block_size: int = 128
    # decode weight-storage dtype (docs/WEIGHT_QUANT.md): "bfloat16" keeps
    # weights in compute dtype; "int8" aliases the established quantize-at-
    # load path (quantized=True, per-channel int8); "int4" packs grouped
    # sub-byte codes streamed by the ops/quant_matmul fused-dequant kernel.
    weight_dtype: str = "bfloat16"

    # --- LoRA ------------------------------------------------------------
    lora_config: Optional[LoraServingConfig] = None

    # --- debug taps (reference config.py:987/:1038) -----------------------
    tensor_capture_config: Optional[TensorCaptureConfig] = None
    tensor_replacement_config: Optional[TensorReplacementConfig] = None

    # --- serving fault containment (runtime/serving.py, runtime/faults.py;
    # docs/SERVING.md "Failure containment") ------------------------------
    # validate requests at admission (token-id range vs vocab, empty/over-
    # long prompts, non-positive budgets): malformed requests get a typed
    # terminal REJECTED verdict instead of raising (or NaN-ing) mid-batch.
    # False restores the legacy raise-late behavior.
    admission_validation: bool = True
    # wall-clock TTL per request in seconds (None = no deadline): requests
    # past it are dropped with terminal reason `deadline_exceeded`, checked
    # at step boundaries. Per-request override: add_request(deadline_s=...).
    request_deadline_s: Optional[float] = None
    # transient dispatch errors retry with capped exponential backoff up to
    # this many times; after that only the in-flight rows fail
    # (FAILED(dispatch_error)) — never the process.
    dispatch_max_retries: int = 2
    # no-forward-progress watchdog: after this many consecutive steps with
    # zero committed tokens / prefill advance / admissions (while work is
    # live), preempt the largest request; a second full window raises
    # WatchdogError with a diagnostic snapshot. 0 disables.
    watchdog_no_progress_steps: int = 256

    # --- misc ------------------------------------------------------------
    seed: int = 0
    # True (default): generate() chains CTE -> decode chunks with
    # device-resident tokens, one sync per call (runtime/application.py).
    # False: block at every chunk boundary (step-accurate debugging).
    async_mode: bool = True
    # seal the jit caches after warmup(): any steady-state retrace/recompile
    # raises instead of silently blowing the latency model (analysis/
    # retrace_guard.py). Env override: NXDI_TPU_RETRACE_GUARD=1.
    retrace_guard: bool = False
    weights_to_skip_layout_optimization: Optional[List[str]] = None
    logical_nc_config: int = 1  # kept for config-surface parity; no-op on TPU
    skip_warmup: bool = False
    save_sharded_checkpoint: bool = False
    compilation_cache_dir: Optional[str] = None
    scratchpad_page_size: Optional[int] = None  # parity no-op

    def __post_init__(self):
        if self.max_batch_size is None:
            self.max_batch_size = self.batch_size
        if self.ctx_batch_size is None:
            self.ctx_batch_size = self.max_batch_size
        if self.tkg_batch_size is None:
            self.tkg_batch_size = self.max_batch_size
        if self.max_context_length is None:
            self.max_context_length = self.seq_len
        if self.max_length is None:
            self.max_length = self.seq_len
        if self.n_active_tokens is None:
            self.n_active_tokens = self.seq_len
        if self.moe_tp_degree is None:
            self.moe_tp_degree = self.tp_degree // self.ep_degree if self.ep_degree > 1 else self.tp_degree
        if self.moe_ep_degree is None:
            self.moe_ep_degree = self.ep_degree
        if self.local_ranks_size is None:
            self.local_ranks_size = self.world_size
        if self.weight_dtype == "bf16":
            self.weight_dtype = "bfloat16"
        if self.weight_dtype == "int8" and not self.quantized:
            # int8 weights already have a first-class path (quantized=True);
            # the weight_dtype spelling is an alias onto it so the knob is
            # one dial across {bfloat16, int8, int4}
            self.quantized = True
        self.validate()

    # world size identical to reference config.py:353-355
    @property
    def world_size(self) -> int:
        return self.tp_degree * self.pp_degree * self.ep_degree * self.data_parallel_degree

    @property
    def torch_dtype(self):  # name kept for API familiarity; returns jnp dtype
        return to_dtype(self.dtype)

    @property
    def jax_dtype(self):
        return to_dtype(self.dtype)

    @property
    def kv_dtype(self):
        return to_dtype(self.kv_cache_dtype) if self.kv_cache_dtype else to_dtype(self.dtype)

    @property
    def kv_quantized(self) -> bool:
        """True when the KV cache stores int8/fp8 codes + scales."""
        return self.kv_cache_dtype in KV_QUANT_DTYPE_NAMES

    @property
    def weight_int4(self) -> bool:
        """True when weights pack to grouped int4 at load (ops/quant_matmul)."""
        return self.weight_dtype == "int4"

    def validate(self):
        """Feature-interaction validation (reference config.py:567-594)."""
        if self.kv_cache_dtype is not None and self.kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r}; supported: "
                f"{KV_CACHE_DTYPES} (int8/fp8 build the quantized cache)"
            )
        if self.pa_pool_bytes is not None:
            if not self.is_block_kv_layout:
                raise ValueError("pa_pool_bytes requires is_block_kv_layout")
            if self.pa_num_blocks is not None:
                raise ValueError(
                    "set pa_num_blocks OR pa_pool_bytes, not both (the pool "
                    "byte budget derives the block count from the cache dtype)"
                )
        if self.request_deadline_s is not None and not self.request_deadline_s > 0:
            raise ValueError(
                "request_deadline_s must be > 0 seconds (None disables "
                "per-request deadlines)"
            )
        if self.dispatch_max_retries < 0:
            raise ValueError(
                "dispatch_max_retries must be >= 0 (0 = fail in-flight rows "
                "on the first transient dispatch error)"
            )
        if self.watchdog_no_progress_steps < 0:
            raise ValueError(
                "watchdog_no_progress_steps must be >= 0 (0 disables the "
                "no-progress watchdog)"
            )
        if self.serving_replicas < 1:
            raise ValueError(
                "serving_replicas must be >= 1 (1 = a single session, no "
                "router layer)"
            )
        if self.router_policy not in ROUTER_POLICIES:
            raise ValueError(
                f"unknown router_policy {self.router_policy!r}; known "
                f"placement policies: {ROUTER_POLICIES}"
            )
        if self.serving_replicas > 1 and not self.is_continuous_batching:
            raise ValueError(
                "serving_replicas > 1 routes over serving sessions: set "
                "is_continuous_batching=True"
            )
        if self.router_prefill_replicas < 0:
            raise ValueError(
                "router_prefill_replicas must be >= 0 (0 = no disaggregated "
                "prefill tier; every replica prefills locally)"
            )
        if self.router_prefill_replicas > 0:
            if self.router_prefill_replicas >= self.serving_replicas:
                raise ValueError(
                    "router_prefill_replicas is carved OUT OF "
                    "serving_replicas: at least one decode replica must "
                    f"remain ({self.router_prefill_replicas} prefill of "
                    f"{self.serving_replicas} total leaves none)"
                )
            if self.is_block_kv_layout:
                raise ValueError(
                    "the disaggregated prefill tier hands KV over into "
                    "contiguous cache lines: router_prefill_replicas > 0 "
                    "does not support is_block_kv_layout (decode replicas "
                    "need the plain contiguous cache)"
                )
        if self.handoff_max_retries < 0:
            raise ValueError(
                "handoff_max_retries must be >= 0 (0 = a hand-off fails its "
                "in-flight request on the first transient failure)"
            )
        if self.handoff_timeout_s is not None and not self.handoff_timeout_s > 0:
            raise ValueError(
                "handoff_timeout_s must be > 0 seconds (None disables the "
                "per-attempt hand-off timeout)"
            )
        if self.attention_dp_degree > 1 and not self.is_continuous_batching:
            raise ValueError("attention_dp_degree > 1 requires is_continuous_batching")
        if self.attention_dp_degree > 1 and self.max_batch_size % self.attention_dp_degree != 0:
            raise ValueError("batch size must divide evenly across attention DP ranks")
        # attention-DP + paged cache: the block pool replicates over the dp
        # axis (batch-parallel attention reads any block); the contiguous
        # cache dp-shards its batch dim instead — see parallel/attention_dp.py
        if self.data_parallel_degree > 1:
            shards = self.attention_dp_degree * self.data_parallel_degree
            if (self.kv_cache_batch_size or self.max_batch_size) % shards != 0:
                raise ValueError(
                    "batch size must be divisible by attention_dp_degree * "
                    "data_parallel_degree"
                )
            if self.enable_fused_speculation:
                raise NotImplementedError(
                    "whole-model DP with fused speculation is not implemented"
                )
            if self.is_block_kv_layout:
                raise NotImplementedError(
                    "whole-model DP with the paged cache is not implemented"
                )
        if self.attention_dp_degree > 1 and self.enable_fused_speculation:
            raise NotImplementedError(
                "attention-DP with fused/EAGLE speculation is not implemented "
                "(the speculation caches are not DP-sharded)"
            )
        if self.attention_dp_degree > 1 and (
            self.kv_cache_batch_size or self.max_batch_size
        ) % self.attention_dp_degree != 0:
            raise ValueError("kv_cache_batch_size must divide across attention DP ranks")
        if self.cp_degree > 1 and self.tp_degree % self.cp_degree != 0:
            raise ValueError("cp_degree must divide tp_degree (cp splits the tp group)")
        if self.tp_degree % (self.cp_degree * self.attention_dp_degree) != 0:
            raise ValueError(
                "cp_degree * attention_dp_degree must divide tp_degree "
                "(both subdivide the TP group)"
            )
        if self.is_chunked_prefill and not self.is_block_kv_layout:
            raise ValueError("chunked prefill requires block KV layout")
        if self.is_chunked_prefill and self.chunked_prefill_config is None:
            self.chunked_prefill_config = ChunkedPrefillConfig()
        if self.is_chunked_prefill and not self.is_continuous_batching:
            raise ValueError("chunked prefill runs through the serving session: "
                             "set is_continuous_batching=True")
        if self.is_prefix_caching and not self.is_block_kv_layout:
            raise ValueError("prefix caching requires block KV layout")
        if self.serving_ragged:
            if not self.is_block_kv_layout:
                raise ValueError(
                    "serving_ragged requires the paged cache "
                    "(is_block_kv_layout=True): the ragged kernel addresses "
                    "rows through block tables"
                )
            if not self.is_continuous_batching:
                raise ValueError(
                    "serving_ragged runs through the serving session: set "
                    "is_continuous_batching=True"
                )
            if self.sliding_window or self.attention_chunk_size:
                raise NotImplementedError(
                    "serving_ragged implements the plain causal+prefix mask "
                    "only (no sliding-window/chunked attention)"
                )
            if (
                self.attention_dp_degree > 1
                or self.cp_degree > 1
                or self.data_parallel_degree > 1
            ):
                raise NotImplementedError(
                    "serving_ragged is single-shard-parallel (tp only)"
                )
        if self.serving_ragged_async and not self.serving_ragged:
            raise ValueError(
                "serving_ragged_async=True pipelines the RAGGED mixed-step "
                "dispatch: set serving_ragged=True (the legacy split path "
                "already pipelines via async_mode)"
            )
        if self.serving_spec_ragged:
            if not self.serving_ragged:
                raise ValueError(
                    "serving_spec_ragged packs spec-verify rows into the "
                    "ragged mixed step: set serving_ragged=True (paged "
                    "cache + continuous batching)"
                )
            if not self.is_chunked_prefill:
                raise ValueError(
                    "serving_spec_ragged requires is_chunked_prefill=True: "
                    "prompt chunks must ride the same mixed dispatch as the "
                    "spec-verify rows (one program identity per step)"
                )
            # 16 == ops/ragged_paged_attention.RAGGED_Q_TILE (kept literal:
            # config validation must not import kernel modules)
            if not 2 <= self.speculation_length <= 16:
                raise ValueError(
                    "serving_spec_ragged needs 2 <= speculation_length <= "
                    "16: a spec-verify segment (last token + drafts) must "
                    "fit one ragged q tile"
                )
            ods = self.on_device_sampling_config
            if ods is not None and getattr(ods, "do_sample", False):
                raise NotImplementedError(
                    "serving_spec_ragged is greedy-only: the packed verify "
                    "computes contiguous-match acceptance on device "
                    "(sampled accept/reject stays on the split "
                    "SpeculativeServingSession path)"
                )
        if (
            self.is_block_kv_layout
            and self.pa_num_blocks is None
            and self.pa_pool_bytes is None
        ):
            self.pa_num_blocks = max(
                1, (self.max_batch_size * self.seq_len + self.pa_block_size - 1) // self.pa_block_size
            )
        if self.enable_eagle_speculation and not self.enable_fused_speculation:
            raise ValueError("EAGLE speculation requires fused speculation")
        if self.is_eagle3 and not self.enable_eagle_speculation:
            raise ValueError("is_eagle3 requires enable_eagle_speculation")
        if self.token_tree_config is not None:
            if not self.enable_eagle_speculation:
                raise ValueError(
                    "token_tree_config requires enable_eagle_speculation "
                    "(trees expand the EAGLE draft; reference eagle/token_tree.py)"
                )
        if self.medusa_speculation_length and self.num_medusa_heads <= 0:
            raise ValueError("medusa requires num_medusa_heads > 0")
        if self.padding_side not in ("right", "left"):
            raise ValueError("padding_side must be 'right' or 'left'")
        if self.quantization_type not in (
            "per_channel_symmetric",
            "per_tensor_symmetric",
            "blockwise",
        ):
            raise ValueError(f"unknown quantization_type {self.quantization_type}")
        if self.weight_dtype not in ("bfloat16", "int8", "int4"):
            raise ValueError(
                f"unknown weight_dtype {self.weight_dtype!r}; supported: "
                "bfloat16 (no conversion), int8 (per-channel quantize-at-"
                "load), int4 (grouped fused-dequant streaming)"
            )
        if self.weight_dtype == "int4":
            if self.quantized:
                raise ValueError(
                    "weight_dtype='int4' and quantized=True are two different "
                    "weight-conversion recipes applied to the same leaves; "
                    "pick one (int8 via weight_dtype='int8' IS quantized=True)"
                )
            if self.quantized_checkpoints_path:
                raise NotImplementedError(
                    "pre-quantized checkpoint artifacts are int8-only; "
                    "weight_dtype='int4' packs at load (refusing to silently "
                    "ignore quantized_checkpoints_path)"
                )
        if self.flash_decoding_enabled and self.cp_degree <= 1:
            raise ValueError(
                "flash decoding on TPU rides the cp mesh axis (S-sharded KV "
                "cache, kvcache.py): set cp_degree > 1 to distribute the "
                "decode softmax (reference num_cores_per_group grouping)"
            )
        if self.num_cores_per_group != 1 and self.num_cores_per_group != self.cp_degree:
            raise ValueError(
                "num_cores_per_group maps onto the cp mesh axis on TPU; it "
                "must equal cp_degree (or 1)"
            )
        expected_moe_tp = (
            self.tp_degree // self.ep_degree if self.ep_degree > 1 else self.tp_degree
        )
        if self.moe_tp_degree != expected_moe_tp or self.moe_ep_degree != self.ep_degree:
            raise NotImplementedError(
                "custom moe_tp/moe_ep degrees are not implemented: experts "
                "shard over the ep mesh axis and expert ffn over (cp, tp) "
                "(parallel/mesh.py); moe degrees follow tp/ep"
            )
        if self.fused_qkv and self.lora_config is not None:
            raise NotImplementedError(
                "fused_qkv with LoRA serving is not supported: adapters "
                "target q/k/v projections individually"
            )
        self._check_unimplemented(UNIMPLEMENTED_FLAGS)

    def _check_unimplemented(self, table: Dict[str, Tuple[Any, str]]):
        for name, (inert, reason) in table.items():
            if getattr(self, name) != inert:
                raise NotImplementedError(
                    f"TpuConfig.{name} is accepted for reference API parity "
                    f"but not implemented yet ({reason}); refusing to "
                    f"silently ignore it"
                )

    # --- serialization ---------------------------------------------------

    def to_dict(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None:
                d[f.name] = None
            elif hasattr(v, "to_dict"):
                d[f.name] = v.to_dict()
            else:
                d[f.name] = v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TpuConfig":
        d = dict(d)
        if d.get("on_device_sampling_config"):
            d["on_device_sampling_config"] = OnDeviceSamplingConfig.from_dict(
                d["on_device_sampling_config"]
            )
        if d.get("chunked_prefill_config"):
            d["chunked_prefill_config"] = ChunkedPrefillConfig.from_dict(d["chunked_prefill_config"])
        if d.get("lora_config"):
            d["lora_config"] = LoraServingConfig.from_dict(d["lora_config"])
        if d.get("tensor_capture_config"):
            d["tensor_capture_config"] = TensorCaptureConfig.from_dict(
                d["tensor_capture_config"]
            )
        if d.get("tensor_replacement_config"):
            d["tensor_replacement_config"] = TensorReplacementConfig.from_dict(
                d["tensor_replacement_config"]
            )
        return cls(**_strict_kwargs(cls, d))


@dataclass
class MoETpuConfig(TpuConfig):
    """MoE extras (reference MoENeuronConfig, config.py:665-713)."""

    capacity_factor: Optional[float] = None  # None = dropless
    glu_mlp: bool = True
    glu_type: str = "glu"
    hidden_act_scaling_factor: float = 1.0
    hidden_act_bias: float = 0.0
    normalize_top_k_affinities: bool = True
    early_expert_affinity_modulation: bool = False
    fused_shared_experts: bool = False
    router_dtype: str = "float32"
    moe_fused_kernel_enabled: Optional[bool] = None
    hybrid_sharding_config: Optional[dict] = None

    def validate(self):
        super().validate()
        if not self.glu_mlp or self.glu_type != "glu":
            raise NotImplementedError(
                "non-GLU expert MLPs are not implemented (experts are "
                "gate/up/down GLU, modules/moe.py)"
            )
        if self.hybrid_sharding_config is not None:
            h = dict(self.hybrid_sharding_config)
            total = self.tp_degree * self.ep_degree
            cte_tp = int(h.get("moe_cte_tp_degree", total))
            cte_ep = int(h.get("moe_cte_ep_degree", 1))
            tkg_tp = int(h.get("moe_tkg_tp_degree", self.tp_degree))
            tkg_ep = int(h.get("moe_tkg_ep_degree", self.ep_degree))
            if tkg_tp * tkg_ep != total or cte_tp * cte_ep != total:
                raise ValueError(
                    "hybrid_sharding_config degrees must multiply to "
                    f"tp_degree*ep_degree={total}: got cte {cte_tp}x{cte_ep}, "
                    f"tkg {tkg_tp}x{tkg_ep}"
                )
            if tkg_tp != self.tp_degree or tkg_ep != self.ep_degree:
                raise NotImplementedError(
                    "the PERSISTENT (decode) expert layout is the mesh's "
                    "tp_degree x ep_degree — set moe_tkg_tp/ep to match and "
                    "express the prefill preference via moe_cte_tp/ep"
                )
            if cte_ep != 1:
                raise NotImplementedError(
                    "hybrid prefill sharding supports moe_cte_ep_degree=1 "
                    "(full-TP prefill experts, GSPMD-resharded in the CTE "
                    "program); other factorings need a second weight copy"
                )
        if self.capacity_factor is not None:
            # loud-fail contract: combinations the capacity path cannot honor
            # must not silently fall back to dense-dropless (modules/moe.py)
            if self.ep_degree > 1:
                raise NotImplementedError(
                    "capacity_factor with expert parallelism is not "
                    "implemented (the dispatch is token-sorted on one shard)"
                )
            if self.quantized and self.quantization_type == "blockwise":
                raise NotImplementedError(
                    "capacity_factor with blockwise-quantized experts is not "
                    "implemented"
                )
        self._check_unimplemented(UNIMPLEMENTED_MOE_FLAGS)


# ---------------------------------------------------------------------------
# InferenceConfig
# ---------------------------------------------------------------------------

CONFIG_FILE = "tpu_config.json"  # reference: neuron_config.json (config.py:22)


class InferenceConfig:
    """TpuConfig + HF model attributes (reference config.py:716-909).

    Model attributes (hidden_size, num_attention_heads, ...) live as instance
    attributes; ``attribute_map`` aliases alternate names onto canonical ones
    (reference config.py:736-758). JSON round-trip embeds the class path so a
    saved artifact reloads the right subclass (reference config.py:823-905).
    """

    # subclasses may list attrs that must exist post-init
    _REQUIRED_ATTRS: Tuple[str, ...] = ()

    def __init__(self, tpu_config: TpuConfig, load_config=None, metadata: dict = None, **kwargs):
        self.tpu_config = tpu_config
        self.attribute_map: Dict[str, str] = {}
        self.metadata = metadata or {}
        if load_config is not None:
            load_config(self)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.add_derived_config()
        self.validate_config()

    # alias for reference-API familiarity
    @property
    def neuron_config(self) -> TpuConfig:
        return self.tpu_config

    def __getattr__(self, name):
        # only called when normal lookup fails
        amap = self.__dict__.get("attribute_map", {})
        if name in amap:
            return getattr(self, amap[name])
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def __setattr__(self, name, value):
        amap = self.__dict__.get("attribute_map", {})
        if name in amap:
            super().__setattr__(amap[name], value)
        else:
            super().__setattr__(name, value)

    def add_derived_config(self):
        """Hook for model plugins to derive attrs (reference modeling_llama.py:311)."""

    def get_required_attributes(self) -> Tuple[str, ...]:
        return self._REQUIRED_ATTRS

    def validate_config(self):
        missing = [a for a in self.get_required_attributes() if not hasattr(self, a)]
        if missing:
            raise ValueError(f"Config missing required attributes: {missing}")

    # --- serialization ---------------------------------------------------

    def to_dict(self) -> dict:
        d = {}
        for k, v in self.__dict__.items():
            if k in ("tpu_config", "attribute_map", "metadata"):
                continue
            if hasattr(v, "to_dict"):
                d[k] = v.to_dict()
            elif _json_safe(v):
                d[k] = v
        d["tpu_config"] = self.tpu_config.to_dict()
        d["_config_class"] = {"module": type(self).__module__, "name": type(self).__name__}
        if isinstance(self.tpu_config, MoETpuConfig):
            d["tpu_config"]["_moe"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "InferenceConfig":
        d = dict(d)
        cls_info = d.pop("_config_class", None)
        config_cls = cls
        # only resolve config classes from inside this package: a JSON artifact
        # is untrusted input and must not trigger arbitrary module imports
        if isinstance(cls_info, dict) and str(cls_info.get("module", "")).startswith(
            "neuronx_distributed_inference_tpu."
        ):
            try:
                import importlib

                mod = importlib.import_module(cls_info["module"])
                candidate = getattr(mod, cls_info["name"])
                if isinstance(candidate, type) and issubclass(candidate, InferenceConfig):
                    config_cls = candidate
            except Exception:
                config_cls = cls
        tc = d.pop("tpu_config", {})
        moe = tc.pop("_moe", False) if isinstance(tc, dict) else False
        tpu_config = (MoETpuConfig if moe else TpuConfig).from_dict(tc)
        obj = config_cls.__new__(config_cls)
        obj.tpu_config = tpu_config
        obj.attribute_map = {}
        obj.metadata = {}
        for k, v in d.items():
            if isinstance(v, dict) and "_config_class" in v:
                v = InferenceConfig.from_dict(v)
            setattr(obj, k, v)
        return obj

    def save(self, path: str):
        """Save next to the compiled artifact (reference application_base.py:299)."""
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, CONFIG_FILE), "w") as f:
            json.dump(self.to_dict(), f, indent=2, default=_default_json)

    @classmethod
    def load(cls, path: str) -> "InferenceConfig":
        fname = path if path.endswith(".json") else os.path.join(path, CONFIG_FILE)
        with open(fname) as f:
            return cls.from_dict(json.load(f))


def _json_safe(v) -> bool:
    if isinstance(v, (str, int, float, bool, type(None))):
        return True
    if isinstance(v, (list, tuple)):
        return all(_json_safe(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _json_safe(x) for k, x in v.items())
    return False


def _default_json(v):
    if hasattr(v, "to_dict"):
        return v.to_dict()
    return str(v)
