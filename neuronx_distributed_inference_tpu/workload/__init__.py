"""Workload engine + SLO goodput subsystem (docs/WORKLOADS.md).

Three pieces, all host-side and fully deterministic from a seed:

- :mod:`.generator` — composable arrival processes (Poisson / bursty on-off
  / diurnal envelope) × heavy-tailed prompt/output length distributions ×
  multi-tenant pools with shared prompt prefixes × per-tenant
  spec-acceptance profiles, emitting a reproducible
  :class:`~.generator.WorkloadTrace` (same seed ⇒ byte-identical JSON).
- :mod:`.driver` — the open-loop driver: steps a
  :class:`~..runtime.router.ServingRouter` (or a single serving session) on
  a virtual clock, admitting each request no earlier than its arrival step;
  refused arrivals retry from a backlog and count against goodput; a seeded
  :class:`~.driver.ChaosPlan` kills a replica mid-run.
- :mod:`.slo` — the SLO scorer: per-request TTFT/ITL deadline attainment
  from the telemetry ``RequestTrace``s (measured from ARRIVAL, so backlog
  wait counts), **goodput** (SLO-met tokens per second), attainment by
  tenant, a time-bucketed goodput series, and the chaos metrics
  (goodput-dip depth + recovery time) extracted from that series.
"""

from neuronx_distributed_inference_tpu.workload.generator import (  # noqa: F401
    Arrival,
    ArrivalSpec,
    TenantProfile,
    WorkloadSpec,
    WorkloadTrace,
    generate,
    make_accept_gate,
    standard_spec,
)
from neuronx_distributed_inference_tpu.workload.driver import (  # noqa: F401
    ChaosPlan,
    VirtualClock,
    WorkloadDriver,
    WorkloadResult,
)
from neuronx_distributed_inference_tpu.workload.slo import (  # noqa: F401
    DipReport,
    RequestScore,
    SloReport,
    extract_dip,
    score,
)
