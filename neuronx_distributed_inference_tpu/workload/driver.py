"""Open-loop workload driver: arrivals on a virtual clock, not a drain.

The bench's historical serving loop is CLOSED-loop: it feeds the next
request whenever the session has a free slot, so the server sets the pace
and can never be overloaded. Production is open-loop — requests arrive when
users send them — and the number that matters is what happens when the
arrival rate and the service rate disagree. This driver runs a
:class:`~..runtime.router.ServingRouter` (or a single serving session) under
a :class:`~.generator.WorkloadTrace`:

- **Virtual clock.** One ``step()`` == one virtual second
  (``step_dt_s``). Construct the sessions / router / telemetry with
  ``clock=VirtualClock().now`` and every wall-clock policy in the stack —
  the PR-7 per-request deadline TTLs, the telemetry ``RequestTrace``
  timestamps the SLO scorer consumes, the replica load EWMAs — runs on
  deterministic virtual time, so a seeded workload drives a byte-identical
  run every time (pinned sequential AND ``router_threading``).
- **Open-loop admission.** A request is offered to the target no earlier
  than its arrival step (``admissions`` records arrival vs admitted step —
  the open-loop pin inspects them). Head-of-line FIFO: a refused arrival
  (``no_slot`` / ``kv_blocks`` / ``backlog``) waits in the driver backlog
  and retries every step — its SLO clock keeps running from ARRIVAL, so
  backlog time counts against goodput; past ``max_backlog_steps`` the
  driver gives up and records the terminal refusal as
  ``nxdi_requests_rejected_total{reason=backlog}`` (the reason the bench's
  clean-traffic containment pin explicitly excludes). Validation verdicts
  are terminal immediately (scored ``never_served``).
- **Commit attribution.** After every step the driver folds each live
  request's committed-token delta into ``step_commits`` — the per-step
  per-request token series :mod:`.slo` buckets into the goodput series the
  chaos metrics (dip depth, recovery time) are extracted from. For a router
  target the count reads only the audited host-snapshot surface
  (``RouterRequest.tokens`` + the current incarnation's committed
  ``generated`` via ``ReplicaHandle.owned``).
- **Chaos.** A seeded :class:`ChaosPlan` kills one alive replica at a fixed
  step mid-run (the PR-10 failover machinery re-queues its requests); the
  driver records which replica died so the scorer can anchor the dip window.
- **Speculation profiles.** When the trace carries per-tenant
  ``spec_accept_rate`` profiles and the target session(s) are speculative,
  the driver installs :func:`~.generator.make_accept_gate` as
  ``session.draft_accept_cap`` — the CPU-harness draft-agreement model that
  makes adaptive draft lengths move per tenant without changing one output
  byte.

Everything here is host bookkeeping: no device fetches (the tpulint
``drive-hot-path`` census bucket pins the driver loop at zero host-sync
calls) and no writes into router/session internals beyond the public
``add_request``/``step``/``kill`` surface.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from neuronx_distributed_inference_tpu.telemetry.tracing import default_session
from neuronx_distributed_inference_tpu.workload.generator import (
    WorkloadTrace,
    make_accept_gate,
)

#: capacity refusal reasons the backlog retries (anything else offered back
#: by the target is a terminal verdict)
RETRYABLE_REFUSALS = frozenset({"no_slot", "kv_blocks", "backlog"})


class VirtualClock:
    """A monotone host clock the driver advances one step at a time. Pass
    ``clock=vc.now`` to sessions / router handles / the telemetry session so
    deadlines, EWMAs and trace timestamps all run on virtual time."""

    def __init__(self, t0: float = 0.0):
        self._t = float(t0)

    def now(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        self._t += float(dt)
        return self._t


@dataclass(frozen=True)
class ChaosPlan:
    """Seeded replica-kill schedule (driver step indices).

    The base plan kills ONE alive replica at ``kill_step``; ``replica=None``
    picks the victim with a seeded draw among the members alive at that
    step — reproducible chaos. Two extensions (ISSUE 15):

    - **tier targeting**: ``tier="prefill"`` draws victims from the
      router's disaggregated prefill tier (``router.prefill_replicas``)
      instead of the decode replicas — the tier-kill scenario whose goodput
      must NOT dip like a decode kill (decode capacity survives; placements
      degrade to local prefill / surviving tier members).
    - **multi-kill**: ``kills=N`` fires N sequential kills starting at
      ``kill_step``, ``gap_steps`` apart, each drawing a fresh seeded
      victim from the tier's then-alive set (kills with nobody left alive
      are skipped, recorded as exhausted).

    Same seed + same trace => byte-identical kill schedule and outputs
    (pinned by tests/test_workload.py)."""

    kill_step: int
    replica: Optional[int] = None
    seed: int = 0
    tier: str = "decode"  # or "prefill" (disaggregated prefill tier)
    kills: int = 1
    gap_steps: int = 1


@dataclass
class AdmissionEvent:
    req_id: str
    arrival_step: int
    admitted_step: int
    attempts: int  # add_request calls it took (1 == admitted on arrival)


@dataclass
class WorkloadResult:
    """One open-loop run, scorer-ready (:func:`workload.slo.score`)."""

    trace: WorkloadTrace
    outputs: Dict[str, List[int]] = field(default_factory=dict)
    statuses: Dict[str, str] = field(default_factory=dict)
    admissions: List[AdmissionEvent] = field(default_factory=list)
    #: terminal driver-level refusals: backlog give-ups + validation rejects
    never_served: Dict[str, str] = field(default_factory=dict)
    #: per driver step: {req_id: tokens committed that step}
    step_commits: List[Dict[str, int]] = field(default_factory=list)
    #: per driver step: the target still held (or could receive) live work
    live_steps: List[bool] = field(default_factory=list)
    backlog_refusals: int = 0  # refused admission attempts (retried)
    steps: int = 0
    step_dt_s: float = 1.0
    chaos: Optional[dict] = None


class WorkloadDriver:
    def __init__(
        self,
        target,
        trace: WorkloadTrace,
        *,
        clock: Optional[VirtualClock] = None,
        telemetry=None,
        step_dt_s: float = 1.0,
        max_backlog_steps: Optional[int] = None,
        chaos: Optional[ChaosPlan] = None,
        max_total_steps: int = 100_000,
    ):
        """``target``: a ServingRouter or a single serving session (detected
        by the ``replicas`` attribute). ``clock``: the virtual clock this
        driver advances — pass the SAME clock's ``now`` into the sessions,
        router handles and telemetry session for a fully deterministic run.
        ``max_backlog_steps``: give up on an arrival stuck in the backlog
        this long (None = retry until served). ``chaos``: optional seeded
        replica kill (router targets only)."""
        self.target = target
        self.trace = trace
        self.clock = clock if clock is not None else VirtualClock()
        self.tel = telemetry if telemetry is not None else default_session()
        self.step_dt_s = float(step_dt_s)
        self.max_backlog_steps = max_backlog_steps
        self.chaos = chaos
        self.max_total_steps = int(max_total_steps)
        self._is_router = hasattr(target, "replicas")
        if chaos is not None and not self._is_router:
            raise ValueError("ChaosPlan needs a router target (replica kill)")
        if chaos is not None:
            if chaos.tier not in ("decode", "prefill"):
                raise ValueError(
                    f"unknown ChaosPlan tier {chaos.tier!r} (decode/prefill)"
                )
            if chaos.tier == "prefill" and not getattr(
                target, "prefill_replicas", None
            ):
                raise ValueError(
                    "ChaosPlan(tier='prefill') needs a router with a "
                    "disaggregated prefill tier (router_prefill_replicas)"
                )
            if chaos.kills < 1 or chaos.gap_steps < 1:
                raise ValueError("ChaosPlan needs kills >= 1, gap_steps >= 1")
        self._chaos_rng = np.random.RandomState(
            chaos.seed if chaos is not None else 0
        )
        self._step = 0
        #: arrivals not yet admitted, FIFO by arrival step (the driver-side
        #: aging queue; refused heads block — later arrivals cannot overtake)
        self._pending = deque(trace.arrivals)
        self._attempts: Dict[str, int] = {}
        self._seen: Dict[str, int] = {}
        self._tracked: List[str] = []  # admitted req ids, commit attribution
        self.result = WorkloadResult(trace=trace, step_dt_s=self.step_dt_s)
        # span-timeline + live-SLO wiring (ISSUE 19): request spans land on
        # tenant tracks, and an attached SloMonitor learns every arrival's
        # clock origin and SLO terms before the drain starts
        if getattr(self.tel, "enabled", False):
            self.tel.set_tenants(trace.tenants_of)
            mon = getattr(self.tel, "slo_monitor", None)
            if mon is not None:
                mon.register_trace(trace, step_dt_s=self.step_dt_s)
        if any(a.spec_accept_rate is not None for a in trace.arrivals):
            self._install_accept_gate()

    # ---- wiring ----------------------------------------------------------

    def _sessions(self) -> List:
        if self._is_router:
            return [h.session for h in self.target.replicas]
        return [self.target]

    def _install_accept_gate(self) -> None:
        """Per-tenant spec-acceptance profiles -> the sessions' CPU-harness
        draft-agreement gate (no-op for non-speculative sessions)."""
        gate = make_accept_gate(self.trace)
        for sess in self._sessions():
            if hasattr(sess, "draft_accept_cap"):
                sess.draft_accept_cap = gate

    # ---- admission (open-loop front edge) --------------------------------

    def _backlog_depth(self) -> int:
        return sum(1 for a in self._pending if a.step <= self._step)

    def _admit_due(self) -> None:
        """Offer every due arrival, head-of-line FIFO: the oldest waiting
        arrival is offered first and a capacity refusal blocks the queue
        for this step (aging — later arrivals cannot claim the capacity an
        older one is waiting for). Terminal verdicts (validation, backlog
        give-up) drop out of the queue as never-served. The backlog
        give-up fires only AFTER a refused offer at the current step: an
        arrival that merely aged behind a blocked head is still offered —
        if capacity just freed it admits, and a give-up never precedes its
        first (or any) offer."""
        while self._pending and self._pending[0].step <= self._step:
            arr = self._pending[0]
            self._attempts[arr.req_id] = self._attempts.get(arr.req_id, 0) + 1
            verdict = self.target.add_request(
                arr.req_id,
                list(arr.input_ids),
                max_new_tokens=arr.max_new_tokens,
                deadline_s=arr.deadline_s,
            )
            if verdict:
                self._pending.popleft()
                self._tracked.append(arr.req_id)
                self.result.admissions.append(AdmissionEvent(
                    req_id=arr.req_id,
                    arrival_step=arr.step,
                    admitted_step=self._step,
                    attempts=self._attempts[arr.req_id],
                ))
                continue
            reason = verdict.reason or "refused"
            if reason in RETRYABLE_REFUSALS:
                self.result.backlog_refusals += 1
                self.tel.workload_refused(reason)
                if (
                    self.max_backlog_steps is not None
                    and self._step - arr.step > self.max_backlog_steps
                ):
                    # the open-loop give-up (this offer was refused AND the
                    # arrival is past its backlog budget): a terminal
                    # refusal the workload layer owns, recorded under the
                    # rejected counter's `backlog` reason — the one the
                    # bench's clean-traffic containment pin excludes
                    # (ISSUE satellite). The next waiting arrival gets its
                    # own offer this step.
                    self._pending.popleft()
                    self.result.never_served[arr.req_id] = "backlog"
                    self.tel.request_rejected(arr.req_id, "backlog")
                    continue
                break  # head-of-line: retry next step, keep FIFO order
            # terminal verdict (validation / never_fits / no_replicas):
            # the request is never served and scores as an SLO miss
            self._pending.popleft()
            self.result.never_served[arr.req_id] = reason

    # ---- chaos -----------------------------------------------------------

    def _maybe_kill(self) -> None:
        """Fire the chaos schedule: kill i (0-based) lands at
        ``kill_step + i * gap_steps``, each drawing a fresh seeded victim
        from the targeted tier's then-alive set. ``result.chaos`` keeps the
        first kill's fields (the scorer's dip anchor) plus the full
        ``events`` list for multi-kill schedules."""
        if self.chaos is None:
            return
        c = self.chaos
        offset = self._step - c.kill_step
        if offset < 0 or offset % c.gap_steps != 0:
            return
        if offset // c.gap_steps >= c.kills:
            return
        if c.tier == "prefill":
            pool = list(getattr(self.target, "prefill_replicas", ()))
        else:
            pool = list(self.target.replicas)
        alive = [h for h in pool if h.alive]
        event = {"step": self._step, "tier": c.tier, "alive_before": len(alive)}
        if not alive:
            event["exhausted"] = True  # schedule outlived the tier
        else:
            if c.replica is not None and offset == 0:
                victims = [h for h in alive if h.replica_id == c.replica]
            else:
                victims = [alive[int(self._chaos_rng.randint(len(alive)))]]
            if not victims:
                return
            victims[0].kill("chaos")
            event["replica"] = victims[0].replica_id
            self.tel.chaos_kill(victims[0].replica_id, c.tier, self._step)
        if self.result.chaos is None:
            self.result.chaos = {
                **event,
                # a prefill-tier kill leaves decode capacity INTACT (the
                # router degrades to local prefill / surviving members), so
                # the scorer's capacity-adjusted recovery target must not
                # assume (N-1)/N decode capacity
                "alive_frac": 1.0 if c.tier == "prefill" else None,
                "events": [],
            }
        self.result.chaos["events"].append(event)

    # ---- commit attribution ----------------------------------------------

    def _committed_of(self, rid: str) -> int:
        """This request's total committed tokens RIGHT NOW, read from the
        audited host-snapshot surface (router: folded failover tokens + the
        current incarnation's committed ``generated``)."""
        if not self._is_router:
            sreq = self.target.requests.get(rid)
            if sreq is None:
                return self._seen.get(rid, 0)
            return len(sreq.generated)
        rreq = self.target.requests.get(rid)
        if rreq is None:
            return self._seen.get(rid, 0)
        total = len(rreq.tokens)
        if not rreq.finished:
            sid = rreq.session_id()
            for h in self.target.replicas:
                if h.owned.get(sid) is rreq:
                    sreq = h.session.requests.get(sid)
                    if sreq is not None:
                        total += len(sreq.generated)
                    break
        return total

    def _record_step(self) -> None:
        commits: Dict[str, int] = {}
        for rid in self._tracked:
            cur = self._committed_of(rid)
            prev = self._seen.get(rid, 0)
            if cur > prev:
                commits[rid] = cur - prev
                self._seen[rid] = cur
        self.result.step_commits.append(commits)
        self.result.live_steps.append(self._has_live_work())
        self.tel.workload_backlog(self._backlog_depth())
        self.tel.workload_step(self._step, commits, self.step_dt_s)
        mon = getattr(self.tel, "slo_monitor", None)
        if mon is not None:
            # verdicts landed during this step fold into ITS window bucket
            mon.tick(self._step)

    def _has_live_work(self) -> bool:
        if self._is_router:
            return bool(self.target.has_live_work)
        sess = self.target
        return bool(sess.active or sess._readmit)

    # ---- stepping --------------------------------------------------------

    def step(self) -> Dict[str, int]:
        """One open-loop tick: admit every due arrival (FIFO, aged), fire
        the chaos plan if this is its step, advance the target one step,
        attribute committed tokens, then advance the virtual clock. Returns
        the target's {req_id: token} step results."""
        self._admit_due()
        self._maybe_kill()
        results = self.target.step()
        self._record_step()
        self._step += 1
        self.result.steps = self._step
        self.clock.advance(self.step_dt_s)
        return results

    def run(self) -> WorkloadResult:
        """Drive to completion: until every arrival was admitted or
        terminally refused AND the target drained. Fails loudly past
        ``max_total_steps`` (an open-loop run that cannot drain is a bug,
        not a hang)."""
        while self._pending or self._has_live_work():
            if self._step >= self.max_total_steps:
                raise RuntimeError(
                    f"workload did not drain within {self.max_total_steps} "
                    f"steps ({len(self._pending)} arrivals pending)"
                )
            self.step()
        mon = getattr(self.tel, "slo_monitor", None)
        if mon is not None:
            # judge stragglers that never reached a session terminal
            # (validation rejects, router-only failures) — the scorer's
            # failed / never_served taxonomy for the same cases
            mon.finalize(self._step)
        self._collect()
        return self.result

    def _collect(self) -> None:
        if self._is_router:
            for rid, rreq in self.target.requests.items():
                self.result.outputs[rid] = list(rreq.tokens)
                self.result.statuses[rid] = rreq.status
        else:
            for rid, sreq in self.target.requests.items():
                self.result.outputs[rid] = list(sreq.generated)
                self.result.statuses[rid] = sreq.status
        for rid, reason in self.result.never_served.items():
            self.result.statuses.setdefault(rid, f"never_served:{reason}")
