"""SLO attainment + goodput scoring over an open-loop workload run.

Raw tok/s rewards a server for finishing work nobody is waiting for
anymore. The serving literature's answer is **goodput under SLO**: only
tokens from requests that met their latency deadlines count. This module
scores one :class:`~.driver.WorkloadResult` against its trace:

- **Per-request attainment.** A request MEETS its SLO iff it finished
  (terminal ``finished`` — validation rejects, backlog give-ups, deadline
  expiries and fault terminals all miss) AND its TTFT — measured from
  ARRIVAL (the workload trace's step), so driver-backlog and router-queue
  wait count — is within the tenant's ``ttft_slo_s`` AND its average
  inter-token latency (first→last token span / (tokens−1), which absorbs
  multi-token fetch amortization and failover gaps) is within
  ``itl_slo_s``. A ``None`` SLO term always passes, so generous-SLO runs
  pin ``attainment == 1.0`` exactly.
- **Goodput.** ``slo_met_tokens`` = committed tokens of SLO-met requests;
  callers divide by wall seconds for a tok/s goodput comparable to the
  closed-loop rows (the report also carries tokens per VIRTUAL second).
- **Time-bucketed series + chaos metrics.** ``step_commits`` from the
  driver, restricted to SLO-met requests and bucketed ``bucket_steps`` at a
  time, is the goodput series; :func:`extract_dip` reads the seeded
  replica-kill's cost off it: ``dip_frac`` (1 − dip/pre-kill baseline) and
  ``recovery_steps`` (kill until the series regains ``recovery_frac`` of
  the CAPACITY-ADJUSTED baseline — after killing 1 of N replicas the
  recoverable level is ``(N−1)/N`` of the pre-kill baseline, so recovery is
  judged against ``recovery_frac × alive_frac × baseline``, not a level the
  surviving capacity cannot reach).

Telemetry: when called with an enabled session, every miss increments
``nxdi_slo_missed_total{kind, tenant}`` (kinds: ``ttft`` / ``itl`` /
``failed`` / ``never_served``) — host-side, post-hoc, TPU107-clean.

Router note: session-level telemetry traces are keyed by the session-side
request id, which carries a ``~fN`` suffix per failover incarnation; the
scorer merges incarnations back onto the base id (earliest first token,
latest last token, summed token counts).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from neuronx_distributed_inference_tpu.telemetry.slo_monitor import judge
from neuronx_distributed_inference_tpu.workload.driver import WorkloadResult
from neuronx_distributed_inference_tpu.workload.generator import base_req_id


@dataclass
class RequestScore:
    req_id: str
    tenant: str
    arrival_s: float
    tokens: int
    finished: bool
    ttft_s: Optional[float] = None
    avg_itl_s: Optional[float] = None
    ttft_ok: bool = True
    itl_ok: bool = True
    miss_kind: Optional[str] = None  # ttft | itl | failed | never_served

    @property
    def met(self) -> bool:
        return self.miss_kind is None


@dataclass
class DipReport:
    """Chaos cost read off the goodput series (bucket units are driver
    steps × ``bucket_steps``)."""

    kill_bucket: int
    baseline: float  # mean pre-kill bucket goodput (tokens/bucket)
    dip_value: float  # worst post-kill bucket
    dip_frac: float  # 1 - dip/baseline, clamped at 0
    recovery_target: float  # recovery_frac * alive_frac * baseline
    recovery_steps: Optional[int]  # kill -> first bucket back at target


@dataclass
class SloReport:
    per_request: List[RequestScore]
    attainment: float
    attainment_by_tenant: Dict[str, float]
    slo_met_tokens: int
    total_tokens: int
    goodput_tok_per_virtual_s: float
    misses_by_kind: Dict[str, int]
    series: List[int] = field(default_factory=list)  # SLO-met tokens/bucket
    bucket_steps: int = 1
    dip: Optional[DipReport] = None


def _traces_by_base(telemetry) -> Dict[str, List]:
    """One pass over the telemetry RequestTraces — the completed deque AND
    the still-open table (a harvested failover incarnation never 'finishes'
    in its session, so its trace stays open) — keyed by the BASE workload
    request id, incarnations merged onto it."""
    out: Dict[str, List] = {}
    for tr in list(telemetry.completed) + list(telemetry.traces.values()):
        out.setdefault(base_req_id(tr.req_id), []).append(tr)
    return out


def extract_dip(
    series: List[float],
    kill_bucket: int,
    *,
    bucket_steps: int = 1,
    warmup_buckets: int = 1,
    alive_frac: float = 1.0,
    recovery_frac: float = 0.8,
    dip_window_buckets: int = 4,
) -> Optional[DipReport]:
    """Dip depth + recovery time from a goodput series. Pure function —
    unit-tested on hand-built series. Returns None when the series cannot
    support the read (kill outside the series, or no pre-kill baseline).

    The dip is read over a BOUNDED window of ``dip_window_buckets`` buckets
    after the kill — the failover transient (harvest + re-queue +
    re-prefill on the survivors) — not the whole tail: every finite run
    eventually drains down to zero as its last requests finish, and a
    global post-kill minimum would report that drain as chaos damage.
    Recovery is the first bucket at/after the dip back at
    ``recovery_frac × alive_frac × baseline``."""
    if not (0 < kill_bucket < len(series)):
        return None
    # the baseline must come from POST-warmup pre-kill buckets: a kill
    # inside the ramp-up window has no steady level to measure a dip
    # against — refusing the read beats silently comparing against the
    # ramp bucket (which understates every dip to ~0)
    pre = series[warmup_buckets:kill_bucket]
    if not pre:
        return None
    baseline = float(sum(pre)) / len(pre)
    if baseline <= 0:
        return None
    window = series[kill_bucket:kill_bucket + max(1, dip_window_buckets)]
    dip_value = float(min(window))
    dip_idx = kill_bucket + window.index(min(window))
    dip_frac = max(0.0, 1.0 - dip_value / baseline)
    target = recovery_frac * alive_frac * baseline
    recovery_steps: Optional[int] = None
    for b in range(dip_idx, len(series)):
        if series[b] >= target:
            recovery_steps = (b - kill_bucket) * bucket_steps
            break
    return DipReport(
        kill_bucket=kill_bucket,
        baseline=baseline,
        dip_value=dip_value,
        dip_frac=round(dip_frac, 4),
        recovery_target=target,
        recovery_steps=recovery_steps,
    )


def score(
    result: WorkloadResult,
    telemetry,
    *,
    bucket_steps: int = 4,
    recovery_frac: float = 0.8,
    alive_frac: Optional[float] = None,
    record: bool = True,
) -> SloReport:
    """Score one run. ``telemetry`` is the TelemetrySession the serving
    stack recorded into (its RequestTraces carry the virtual-clock
    timestamps); ``record=True`` additionally increments
    ``nxdi_slo_missed_total{kind, tenant}`` per miss."""
    trace = result.trace
    dt = result.step_dt_s
    scores: List[RequestScore] = []
    misses: Dict[str, int] = {}
    traces_of = _traces_by_base(telemetry)
    for arr in trace.arrivals:
        rid = arr.req_id
        arrival_s = arr.step * dt
        tokens = len(result.outputs.get(rid, ()))
        status = result.statuses.get(rid, "never_served")
        finished = status == "finished"
        sc = RequestScore(
            req_id=rid, tenant=arr.tenant, arrival_s=arrival_s,
            tokens=tokens, finished=finished,
        )
        trs = traces_of.get(rid, [])
        firsts = [t.t_first_token for t in trs if t.t_first_token is not None]
        lasts = [t.t_last_token for t in trs if t.t_last_token is not None]
        n_tok = sum(t.tokens for t in trs)
        if firsts:
            sc.ttft_s = min(firsts) - arrival_s
            if n_tok > 1 and lasts:
                sc.avg_itl_s = (max(lasts) - min(firsts)) / (n_tok - 1)
        # the per-request verdict routes through the SAME predicate the live
        # SloMonitor applies mid-run (telemetry/slo_monitor.py) — the two
        # surfaces can never drift (pinned by tests/test_obs_timeline.py)
        if arr.ttft_slo_s is not None:
            sc.ttft_ok = sc.ttft_s is not None and sc.ttft_s <= arr.ttft_slo_s
        if arr.itl_slo_s is not None and sc.avg_itl_s is not None:
            sc.itl_ok = sc.avg_itl_s <= arr.itl_slo_s
        sc.miss_kind = judge(
            finished=finished,
            served=rid not in result.never_served and bool(firsts),
            ttft_s=sc.ttft_s,
            avg_itl_s=sc.avg_itl_s,
            ttft_slo_s=arr.ttft_slo_s,
            itl_slo_s=arr.itl_slo_s,
        )
        if sc.miss_kind is not None:
            misses[sc.miss_kind] = misses.get(sc.miss_kind, 0) + 1
            if record:
                telemetry.slo_missed(sc.miss_kind, arr.tenant)
        scores.append(sc)

    met_ids = {s.req_id for s in scores if s.met}
    slo_met_tokens = sum(s.tokens for s in scores if s.met)
    total_tokens = sum(s.tokens for s in scores)
    by_tenant: Dict[str, List[RequestScore]] = {}
    for s in scores:
        by_tenant.setdefault(s.tenant, []).append(s)
    attainment_by_tenant = {
        t: sum(1 for s in ss if s.met) / len(ss)
        for t, ss in sorted(by_tenant.items())
    }
    attainment = (
        sum(1 for s in scores if s.met) / len(scores) if scores else 0.0
    )

    # the time-bucketed goodput series: SLO-met tokens per bucket, trimmed
    # to the live span (trailing idle steps would fake a terminal dip).
    # live_steps is recorded AFTER each step, so the step that commits the
    # run's LAST tokens reads not-live — a step with commits always stays
    # in the span (and in virtual_span), only genuinely idle tails trim.
    live = result.live_steps
    end = len(result.step_commits)
    while end > 0 and not (
        (live[end - 1] if end - 1 < len(live) else True)
        or result.step_commits[end - 1]
    ):
        end -= 1
    series: List[int] = []
    for i in range(0, end, bucket_steps):
        series.append(sum(
            n
            for commits in result.step_commits[i:i + bucket_steps]
            for rid, n in commits.items()
            if rid in met_ids
        ))
    virtual_span = max(1, end) * dt
    dip = None
    if result.chaos is not None:
        af = alive_frac
        if af is None:
            # the driver may pin capacity explicitly: a PREFILL-tier kill
            # leaves decode capacity intact (alive_frac 1.0 — the router
            # degrades to local prefill), so the recovery target must not
            # assume a decode replica died
            af = result.chaos.get("alive_frac")
        if af is None:
            # capacity left after the kill(s): (N-k)/N of the replicas that
            # were alive when the chaos plan first fired (k = kills that
            # actually landed; multi-kill schedules record them in events)
            n_before = max(1, int(result.chaos.get("alive_before", 2)))
            events = result.chaos.get("events") or [{}]
            n_killed = max(
                1, sum(1 for e in events if not e.get("exhausted"))
            )
            af = max(1, n_before - n_killed) / n_before
        dip = extract_dip(
            series,
            result.chaos["step"] // bucket_steps,
            bucket_steps=bucket_steps,
            alive_frac=af,
            recovery_frac=recovery_frac,
        )
    return SloReport(
        per_request=scores,
        attainment=round(attainment, 4),
        attainment_by_tenant=attainment_by_tenant,
        slo_met_tokens=slo_met_tokens,
        total_tokens=total_tokens,
        goodput_tok_per_virtual_s=round(slo_met_tokens / virtual_span, 4),
        misses_by_kind=misses,
        series=series,
        bucket_steps=bucket_steps,
        dip=dip,
    )
