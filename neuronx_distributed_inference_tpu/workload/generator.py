"""Seeded workload generation: arrivals × lengths × tenants × spec profiles.

Every serving number this repo produced before the workload engine came from
draining a fixed 8-request mix to completion. Production traffic is nothing
like that: it is OPEN-LOOP (requests arrive on their own schedule, whether
or not the server is ready), bursty or diurnal, multi-tenant (pools of users
sharing system-prompt prefixes), and heavy-tailed in both prompt and output
length. This module builds that traffic shape as data:

- **Arrival processes** (:class:`ArrivalSpec`): per-step arrival counts are
  Poisson draws around a rate envelope — constant (``poisson``), bursty
  on/off square wave (``onoff``), or a sinusoidal diurnal envelope
  (``diurnal``). One step of the envelope == one driver step == one virtual
  second (:mod:`.driver`).
- **Length distributions**: prompt lengths are lognormal (the classic
  heavy-ish body), output budgets are Zipf (the genuinely heavy tail), both
  clipped to the per-tenant bounds so every request stays admissible within
  the session's bucket limits.
- **Tenant pools** (:class:`TenantProfile`): each arrival draws a tenant by
  weight; a tenant's requests share a prompt PREFIX (drawn once per trace —
  the system-prompt / multi-turn regime prefix caching and the router's
  ``cache_aware`` placement exist for) and carry the tenant's TTFT/ITL SLOs
  and optional PR-7 wall-clock deadline.
- **Spec-acceptance profiles**: a tenant's ``spec_accept_rate`` models how
  often a draft model agrees with the target on that tenant's text (prose-ish
  high, code-ish low). On the CPU harness — where random weights pin real
  draft agreement near zero or (same weights) near one — the profile is
  consumed through :func:`make_accept_gate`: a deterministic per-(request,
  round, position) agreement draw that CAPS the accepted draft count of a
  verify round. Capping acceptance is output-invariant (capped tokens are
  the target's own greedy tokens and are simply regenerated in later
  rounds), so the adaptive draft-length machinery actually moves per tenant
  while token streams stay byte-identical.

Determinism contract: :func:`generate` is a pure function of its
:class:`WorkloadSpec` — same seed ⇒ byte-identical trace (pinned via
:meth:`WorkloadTrace.digest`), and the JSON round trip
(:meth:`WorkloadTrace.dumps` / :func:`WorkloadTrace.loads`) is exact, so a
trace can be archived next to a bench artifact and replayed.
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

#: arrival-process kinds ArrivalSpec.kind may take
ARRIVAL_KINDS = ("poisson", "onoff", "diurnal")


def base_req_id(rid: str) -> str:
    """Session-side request id -> workload request id: the router suffixes
    each failover incarnation ``~fN`` (RouterRequest.session_id); the
    workload layer (tenant profiles, SLO scoring) always speaks base ids."""
    head, sep, tail = rid.rpartition("~f")
    if sep and tail.isdigit():
        return head
    return rid


@dataclass(frozen=True)
class ArrivalSpec:
    """Rate envelope for the per-step Poisson arrival draws.

    ``rate`` is the mean arrivals per driver step (the ON-phase rate for
    ``onoff``, the PEAK rate for ``diurnal``). ``onoff`` alternates
    ``period_on`` steps at ``rate`` with ``period_off`` steps at
    ``off_rate``; ``diurnal`` scales ``rate`` by a sinusoid bounded below at
    ``diurnal_floor`` of the peak (one full period every
    ``diurnal_period`` steps)."""

    kind: str = "poisson"
    rate: float = 1.0
    off_rate: float = 0.0
    period_on: int = 8
    period_off: int = 8
    diurnal_period: int = 64
    diurnal_floor: float = 0.25

    def __post_init__(self):
        if self.kind not in ARRIVAL_KINDS:
            raise ValueError(
                f"unknown arrival kind {self.kind!r}; known: {ARRIVAL_KINDS}"
            )
        if self.rate < 0 or self.off_rate < 0:
            raise ValueError("arrival rates must be >= 0")

    def rate_at(self, step: int) -> float:
        """The envelope value at one driver step."""
        if self.kind == "poisson":
            return self.rate
        if self.kind == "onoff":
            period = max(1, self.period_on + self.period_off)
            return (
                self.rate
                if (step % period) < self.period_on
                else self.off_rate
            )
        # diurnal: peak `rate`, trough `diurnal_floor * rate`
        phase = 2.0 * math.pi * step / max(1, self.diurnal_period)
        depth = 0.5 * (1.0 + math.sin(phase))  # in [0, 1]
        return self.rate * (
            self.diurnal_floor + (1.0 - self.diurnal_floor) * depth
        )


@dataclass(frozen=True)
class TenantProfile:
    """One tenant pool: traffic share, length distributions, shared prompt
    prefix, SLO class, and the spec-acceptance profile. SLOs are in VIRTUAL
    seconds (one driver step == one virtual second by default); ``None``
    disables that SLO term. ``deadline_s`` rides the PR-7 wall-clock TTL
    (``add_request(deadline_s=...)``) so overruns terminate server-side as
    ``deadline_exceeded``, not just in post-hoc scoring."""

    name: str
    weight: float = 1.0
    shared_prefix_len: int = 0
    prompt_len_mu: float = 2.5  # lognormal of tokens
    prompt_len_sigma: float = 0.5
    min_prompt_len: int = 1
    max_prompt_len: int = 32
    output_zipf_a: float = 2.5  # Zipf tail exponent for output budgets
    min_output_len: int = 1
    max_output_len: int = 16
    ttft_slo_s: Optional[float] = None
    itl_slo_s: Optional[float] = None
    deadline_s: Optional[float] = None
    spec_accept_rate: Optional[float] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")
        if not (0 < self.min_prompt_len <= self.max_prompt_len):
            raise ValueError(f"tenant {self.name!r}: bad prompt bounds")
        if not (0 < self.min_output_len <= self.max_output_len):
            raise ValueError(f"tenant {self.name!r}: bad output bounds")
        if self.shared_prefix_len >= self.max_prompt_len:
            raise ValueError(
                f"tenant {self.name!r}: shared_prefix_len must leave room "
                "for at least one per-request suffix token"
            )
        if self.spec_accept_rate is not None and not (
            0.0 <= self.spec_accept_rate <= 1.0
        ):
            raise ValueError(f"tenant {self.name!r}: accept rate in [0, 1]")


@dataclass(frozen=True)
class WorkloadSpec:
    """Everything :func:`generate` needs; pure data, JSON-able."""

    seed: int
    n_requests: int
    vocab_size: int
    arrival: ArrivalSpec = field(default_factory=ArrivalSpec)
    tenants: Tuple[TenantProfile, ...] = (TenantProfile(name="default"),)
    max_steps: int = 100_000  # envelope safety bound (rate ~0 tails)

    def __post_init__(self):
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if self.vocab_size < 2:
            raise ValueError("vocab_size must be >= 2")
        if not self.tenants:
            raise ValueError("at least one tenant profile")


@dataclass(frozen=True)
class Arrival:
    """One request of the trace, fully materialized (tokens included) so a
    replayed trace needs no rng."""

    req_id: str
    step: int
    tenant: str
    input_ids: Tuple[int, ...]
    max_new_tokens: int
    ttft_slo_s: Optional[float] = None
    itl_slo_s: Optional[float] = None
    deadline_s: Optional[float] = None
    spec_accept_rate: Optional[float] = None


@dataclass
class WorkloadTrace:
    """The reproducible arrival trace: spec + materialized arrivals (step
    order, stable req_ids). ``dumps()``/``loads()`` round-trip exactly;
    ``digest()`` is the byte-identity pin."""

    spec: WorkloadSpec
    arrivals: List[Arrival]

    def to_json(self) -> dict:
        return {
            "spec": asdict(self.spec),
            "arrivals": [asdict(a) for a in self.arrivals],
        }

    def dumps(self) -> str:
        """Canonical JSON (sorted keys, no whitespace drift) — two traces
        are byte-identical iff their dumps() are equal."""
        return json.dumps(self.to_json(), sort_keys=True, separators=(",", ":"))

    def digest(self) -> str:
        return hashlib.sha256(self.dumps().encode()).hexdigest()

    @staticmethod
    def loads(payload) -> "WorkloadTrace":
        d = json.loads(payload) if isinstance(payload, str) else payload
        sd = dict(d["spec"])
        sd["arrival"] = ArrivalSpec(**sd["arrival"])
        sd["tenants"] = tuple(
            TenantProfile(**t) for t in sd["tenants"]
        )
        spec = WorkloadSpec(**sd)
        arrivals = [
            Arrival(**{**a, "input_ids": tuple(a["input_ids"])})
            for a in d["arrivals"]
        ]
        return WorkloadTrace(spec=spec, arrivals=arrivals)

    @property
    def tenants_of(self) -> Dict[str, str]:
        return {a.req_id: a.tenant for a in self.arrivals}

    @property
    def arrival_steps(self) -> Dict[str, int]:
        return {a.req_id: a.step for a in self.arrivals}


def generate(spec: WorkloadSpec) -> WorkloadTrace:
    """Materialize the trace: walk the rate envelope step by step, drawing
    per-step Poisson arrival counts, then per arrival a weighted tenant, a
    lognormal prompt length (tenant prefix + random suffix) and a Zipf
    output budget — all from ONE seeded RandomState, so the whole trace is a
    pure function of the spec."""
    rng = np.random.RandomState(spec.seed)
    tenants = spec.tenants
    weights = np.asarray([t.weight for t in tenants], np.float64)
    weights = weights / weights.sum()
    # tenant shared prefixes drawn FIRST (order-stable): one per tenant, so
    # every request of a tenant pool shares the same leading blocks
    prefixes = {
        t.name: tuple(
            int(x)
            for x in rng.randint(0, spec.vocab_size, size=t.shared_prefix_len)
        )
        for t in tenants
    }
    arrivals: List[Arrival] = []
    step = 0
    while len(arrivals) < spec.n_requests:
        if step >= spec.max_steps:
            raise ValueError(
                f"arrival envelope produced only {len(arrivals)}/"
                f"{spec.n_requests} arrivals within max_steps={spec.max_steps}"
                " — raise the rate or max_steps"
            )
        n = int(rng.poisson(spec.arrival.rate_at(step)))
        for _ in range(min(n, spec.n_requests - len(arrivals))):
            t = tenants[int(rng.choice(len(tenants), p=weights))]
            prompt_len = int(np.clip(
                int(round(rng.lognormal(t.prompt_len_mu, t.prompt_len_sigma))),
                max(t.min_prompt_len, t.shared_prefix_len + 1),
                t.max_prompt_len,
            ))
            suffix_len = prompt_len - t.shared_prefix_len
            suffix = tuple(
                int(x) for x in rng.randint(0, spec.vocab_size, size=suffix_len)
            )
            out_len = int(np.clip(
                t.min_output_len + int(rng.zipf(t.output_zipf_a)) - 1,
                t.min_output_len,
                t.max_output_len,
            ))
            i = len(arrivals)
            arrivals.append(Arrival(
                req_id=f"{t.name}-{i:04d}",
                step=step,
                tenant=t.name,
                input_ids=prefixes[t.name] + suffix,
                max_new_tokens=out_len,
                ttft_slo_s=t.ttft_slo_s,
                itl_slo_s=t.itl_slo_s,
                deadline_s=t.deadline_s,
                spec_accept_rate=t.spec_accept_rate,
            ))
        step += 1
    return WorkloadTrace(spec=spec, arrivals=arrivals)


def make_accept_gate(trace: WorkloadTrace, seed: Optional[int] = None):
    """Build the CPU-harness draft-agreement gate for a speculative serving
    session (``session.draft_accept_cap``): per verify round it returns how
    many of the round's drafted tokens "agree", drawn per (request, round,
    position) from a counter-free hash of the seed — deterministic under ANY
    step interleaving (sequential or thread-per-replica routing), with
    contiguous-match semantics (the draw stops at the first disagreement,
    the geometric acceptance model speculative decoding is analyzed under).

    Returns None (no cap) for requests whose tenant carries no profile.
    Capping is output-invariant: the accepted window holds the TARGET's own
    greedy tokens, so accepting fewer merely defers them to later rounds —
    byte-identical streams, lower measured acceptance, and the adaptive
    draft-length policy reacts exactly as it would to real disagreement."""
    profiles = {
        a.req_id: a.spec_accept_rate
        for a in trace.arrivals
        if a.spec_accept_rate is not None
    }
    gate_seed = trace.spec.seed if seed is None else seed
    rounds: Dict[str, int] = {}

    def gate(req_id: str, drafted: int) -> Optional[int]:
        # the session calls with ITS request id, which carries a `~fN`
        # suffix per router-failover incarnation (RouterRequest.session_id)
        # — the tenant profile (and the round counter, so the agreement
        # sequence continues across incarnations) follows the BASE id
        req_id = base_req_id(req_id)
        rate = profiles.get(req_id)
        if rate is None:
            return None
        i = rounds.get(req_id, 0)
        rounds[req_id] = i + 1
        agreed = 0
        for j in range(drafted):
            h = hashlib.sha256(
                f"{gate_seed}:{req_id}:{i}:{j}".encode()
            ).digest()
            u = int.from_bytes(h[:8], "big") / 2.0**64
            if u >= rate:
                break  # contiguous-match: first disagreement ends the round
            agreed += 1
        return agreed

    return gate


def standard_spec(
    *,
    seed: int = 0,
    n_requests: int = 16,
    vocab_size: int = 32000,
    arrival_kind: str = "poisson",
    rate: float = 1.0,
    n_tenants: int = 2,
    shared_prefix_len: int = 16,
    max_prompt_len: int = 32,
    min_output_len: int = 1,
    max_output_len: int = 16,
    ttft_slo_s: Optional[float] = None,
    itl_slo_s: Optional[float] = None,
    deadline_s: Optional[float] = None,
    spec_profiles: bool = False,
) -> WorkloadSpec:
    """The stock multi-tenant spec the demo CLI and the bench goodput rows
    share: ``n_tenants`` pools alternating prose-ish (high draft agreement)
    and code-ish (low) profiles, each with its own shared prefix, equal
    weights, common length bounds and one SLO class. A convenience, not a
    constraint — build WorkloadSpec directly for anything richer."""
    tenants = []
    for i in range(max(1, n_tenants)):
        prose = i % 2 == 0
        tenants.append(TenantProfile(
            name=("prose" if prose else "code") + str(i),
            weight=1.0,
            shared_prefix_len=max(0, min(shared_prefix_len,
                                         max_prompt_len - 8)),
            prompt_len_mu=math.log(max(2.0, max_prompt_len / 2.0)),
            prompt_len_sigma=0.5,
            max_prompt_len=max_prompt_len,
            min_output_len=min(min_output_len, max_output_len),
            max_output_len=max_output_len,
            ttft_slo_s=ttft_slo_s,
            itl_slo_s=itl_slo_s,
            deadline_s=deadline_s,
            spec_accept_rate=(
                (0.9 if prose else 0.2) if spec_profiles else None
            ),
        ))
    if arrival_kind == "onoff":
        arrival = ArrivalSpec(kind="onoff", rate=rate, off_rate=0.0,
                              period_on=4, period_off=8)
    elif arrival_kind == "diurnal":
        arrival = ArrivalSpec(kind="diurnal", rate=rate, diurnal_period=32)
    else:
        arrival = ArrivalSpec(kind="poisson", rate=rate)
    return WorkloadSpec(
        seed=seed,
        n_requests=n_requests,
        vocab_size=vocab_size,
        arrival=arrival,
        tenants=tuple(tenants),
    )
