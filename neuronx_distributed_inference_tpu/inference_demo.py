"""inference_demo-style CLI: compile / load / generate / accuracy / benchmark.

TPU-native re-design of the reference CLI
(reference: src/neuronx_distributed_inference/inference_demo.py — argparse
flags map 1:1 onto config fields :94-389; orchestration run_inference :458).

Usage:
    python -m neuronx_distributed_inference_tpu.inference_demo \
        --model-type llama --task-type causal-lm run \
        --model-path /path/to/hf/checkpoint \
        --compiled-model-path /tmp/compiled \
        --batch-size 1 --seq-len 1024 --tp-degree 1 \
        --prompt "I believe the meaning of life is" \
        --benchmark --check-accuracy-mode token-matching
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from neuronx_distributed_inference_tpu.config import (
    InferenceConfig,
    OnDeviceSamplingConfig,
    TpuConfig,
)
from neuronx_distributed_inference_tpu.models.registry import MODEL_REGISTRY
from neuronx_distributed_inference_tpu.runtime.application import TpuModelForCausalLM
from neuronx_distributed_inference_tpu.utils.hf_adapter import load_pretrained_config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="inference_demo", description=__doc__)
    p.add_argument("--model-type", default="llama", choices=sorted(MODEL_REGISTRY))
    p.add_argument("--task-type", default="causal-lm", choices=["causal-lm"])
    sub = p.add_subparsers(dest="action", required=True)
    run = sub.add_parser("run", help="compile, load, and generate")

    # paths
    run.add_argument("--model-path", required=True)
    run.add_argument("--compiled-model-path", default=None)
    run.add_argument("--random-weights", action="store_true",
                     help="skip checkpoint load; random weights (perf/testing)")

    # core shapes (reference inference_demo.py:94-180)
    run.add_argument("--batch-size", type=int, default=1)
    run.add_argument("--seq-len", type=int, default=1024)
    run.add_argument("--max-context-length", type=int, default=None)
    run.add_argument("--dtype", default="bfloat16",
                     choices=["bfloat16", "float32", "float16"])

    # parallelism (reference config.py:333-361)
    run.add_argument("--tp-degree", type=int, default=1)
    run.add_argument("--cp-degree", type=int, default=1)
    run.add_argument("--ep-degree", type=int, default=1)
    run.add_argument("--attention-dp-degree", type=int, default=1)

    # bucketing
    run.add_argument("--enable-bucketing", action="store_true", default=True)
    run.add_argument("--no-bucketing", dest="enable_bucketing", action="store_false")
    run.add_argument("--context-encoding-buckets", type=int, nargs="+", default=None)
    run.add_argument("--token-generation-buckets", type=int, nargs="+", default=None)

    # sampling
    run.add_argument("--on-device-sampling", action="store_true")
    run.add_argument("--do-sample", action="store_true")
    run.add_argument("--top-k", type=int, default=1)
    run.add_argument("--top-p", type=float, default=1.0)
    run.add_argument("--temperature", type=float, default=1.0)

    # quantization (reference --quantized*)
    run.add_argument("--quantized", action="store_true")
    run.add_argument("--quantization-type", default="per_channel_symmetric")
    run.add_argument("--quantization-dtype", default="int8")
    run.add_argument("--kv-cache-dtype", default=None)

    # speculation
    run.add_argument("--draft-model-path", default=None)
    run.add_argument("--draft-model-type", default=None,
                     help="model_type of the draft (default: same as target; "
                          "llama-eagle for EAGLE drafts)")
    run.add_argument("--speculation-length", type=int, default=0)
    run.add_argument("--enable-fused-speculation", action="store_true")
    run.add_argument("--enable-eagle-speculation", action="store_true")
    run.add_argument("--assisted-decoding", action="store_true",
                     help="vanilla (unfused) draft-assisted decoding: draft "
                          "and target compiled independently")

    # generation
    run.add_argument("--prompt", action="append", dest="prompts", default=None)
    run.add_argument("--max-new-tokens", type=int, default=64)

    # eval
    run.add_argument("--benchmark", action="store_true")
    run.add_argument("--check-accuracy-mode", default="skip",
                     choices=["skip", "token-matching", "logit-matching"])
    run.add_argument("--divergence-difference-tol", type=float, default=0.001)
    run.add_argument("--num-runs", type=int, default=5)
    run.add_argument("--skip-warmup", action="store_true")

    # observability (reference inference_demo.py:329-334 + profiling)
    run.add_argument("--input-capture-save-dir", default=None,
                     help="directory for input snapshots / divergence capture")
    run.add_argument("--capture-indices", nargs="+", default=None,
                     help="dispatch indices to snapshot, or 'auto' to capture "
                          "only when the accuracy check diverges")
    run.add_argument("--profile-dir", default=None,
                     help="capture a jax.profiler device trace of generation "
                          "into this directory (view with tensorboard/XProf)")
    run.add_argument("--debug-io", action="store_true",
                     help="log every dispatch's input shapes and output tokens")
    return p


def create_tpu_config(args) -> TpuConfig:
    """CLI flags -> TpuConfig (reference create_neuron_config,
    inference_demo.py:416-422)."""
    ods = None
    if args.on_device_sampling or args.do_sample:
        ods = OnDeviceSamplingConfig(
            do_sample=args.do_sample,
            top_k=args.top_k,
            top_p=args.top_p,
            temperature=args.temperature,
        )
    return TpuConfig(
        batch_size=args.batch_size,
        seq_len=args.seq_len,
        max_context_length=args.max_context_length,
        dtype=args.dtype,
        tp_degree=args.tp_degree,
        cp_degree=args.cp_degree,
        ep_degree=args.ep_degree,
        attention_dp_degree=args.attention_dp_degree,
        enable_bucketing=args.enable_bucketing,
        context_encoding_buckets=args.context_encoding_buckets,
        token_generation_buckets=args.token_generation_buckets,
        on_device_sampling_config=ods,
        quantized=args.quantized,
        quantization_type=args.quantization_type,
        quantization_dtype=args.quantization_dtype,
        kv_cache_dtype=args.kv_cache_dtype,
        speculation_length=args.speculation_length,
        enable_fused_speculation=args.enable_fused_speculation,
        skip_warmup=args.skip_warmup,
        output_logits=args.check_accuracy_mode == "logit-matching",
    )


def run_inference(args) -> int:
    """Orchestration (reference run_inference, inference_demo.py:458)."""
    from neuronx_distributed_inference_tpu.models.registry import get_model_builder

    tpu_config = create_tpu_config(args)
    builder_cls = get_model_builder(args.model_type)
    config_cls = getattr(builder_cls, "config_cls", InferenceConfig)
    load_config = load_pretrained_config(args.model_path)
    config = config_cls(tpu_config, load_config=load_config)

    if args.assisted_decoding and (
        args.enable_fused_speculation or args.enable_eagle_speculation
    ):
        raise ValueError(
            "--assisted-decoding is the unfused path; it conflicts with "
            "--enable-fused-speculation/--enable-eagle-speculation"
        )
    if args.assisted_decoding and args.do_sample:
        raise NotImplementedError(
            "assisted decoding is greedy-only; sampled speculation runs "
            "through --enable-fused-speculation (multinomial accept/reject)"
        )
    fused_spec = args.enable_fused_speculation or args.enable_eagle_speculation or (
        args.draft_model_path and args.speculation_length >= 2
        and not args.assisted_decoding
    )
    assisted = args.assisted_decoding
    print(f"[inference_demo] building {args.model_type} app "
          f"(tp={args.tp_degree} ep={args.ep_degree} fused_spec={bool(fused_spec)} "
          f"eagle={args.enable_eagle_speculation} assisted={assisted})",
          file=sys.stderr)
    t0 = time.time()
    draft_app = None
    if fused_spec:
        from neuronx_distributed_inference_tpu.config import FusedSpecConfig
        from neuronx_distributed_inference_tpu.runtime.fused_spec import (
            TpuEagleSpecModelForCausalLM,
            TpuFusedSpecModelForCausalLM,
        )

        if not args.draft_model_path:
            raise ValueError("fused/eagle speculation requires --draft-model-path")
        tpu_config.enable_fused_speculation = True
        tpu_config.enable_eagle_speculation = args.enable_eagle_speculation
        draft_type = args.draft_model_type or (
            "llama-eagle" if args.enable_eagle_speculation else args.model_type
        )
        draft_builder_cls = get_model_builder(draft_type)
        draft_config_cls = getattr(draft_builder_cls, "config_cls", InferenceConfig)
        draft_config = draft_config_cls(
            create_tpu_config(args), load_config=load_pretrained_config(args.draft_model_path)
        )
        draft_config.model_type = draft_type
        config.fused_spec_config = FusedSpecConfig(
            draft_model_name=args.draft_model_path, draft_config=draft_config
        )
        app_cls = (
            TpuEagleSpecModelForCausalLM
            if args.enable_eagle_speculation
            else TpuFusedSpecModelForCausalLM
        )
        app = app_cls(args.model_path, config, draft_model_path=args.draft_model_path)
        app.load(random_weights=args.random_weights)
    else:
        app = TpuModelForCausalLM(args.model_path, config)
        app.load(random_weights=args.random_weights)
        if assisted:
            if not args.draft_model_path:
                raise ValueError("--assisted-decoding requires --draft-model-path")
            draft_type = args.draft_model_type or args.model_type
            draft_builder_cls = get_model_builder(draft_type)
            draft_config_cls = getattr(draft_builder_cls, "config_cls", InferenceConfig)
            draft_config = draft_config_cls(
                create_tpu_config(args),
                load_config=load_pretrained_config(args.draft_model_path),
            )
            draft_config.model_type = draft_type
            draft_app = TpuModelForCausalLM(args.draft_model_path, draft_config)
            draft_app.load(random_weights=args.random_weights)
    print(f"[inference_demo] load: {time.time()-t0:.1f}s", file=sys.stderr)
    if not fused_spec:
        t0 = time.time()
        app.compile(args.compiled_model_path)
        print(f"[inference_demo] compile+warmup: {time.time()-t0:.1f}s", file=sys.stderr)

    # tokenize prompts
    prompts = args.prompts or ["I believe the meaning of life is"]
    try:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.model_path)
        enc = tok(prompts, return_tensors="np", padding=True, padding_side="right")
        input_ids = enc["input_ids"]
        attention_mask = enc["attention_mask"]
    except Exception as e:
        print(f"[inference_demo] tokenizer unavailable ({e}); using raw ids",
              file=sys.stderr)
        input_ids = np.array([[1] + [i % 100 + 2 for i in range(15)]] * len(prompts))
        attention_mask = np.ones_like(input_ids)
        tok = None

    eos_token_id = getattr(tok, "eos_token_id", None) if tok else None
    gen_kwargs = dict(max_new_tokens=args.max_new_tokens, eos_token_id=eos_token_id)
    if args.do_sample:
        gen_kwargs.update(
            top_k=args.top_k, top_p=args.top_p, temperature=args.temperature
        )
    if args.debug_io:
        from neuronx_distributed_inference_tpu.utils.snapshot import enable_debug_logging

        enable_debug_logging()
    capture_hook = None
    if args.input_capture_save_dir and args.capture_indices and args.capture_indices != ["auto"]:
        from neuronx_distributed_inference_tpu.utils.snapshot import install_input_capture

        capture_hook = install_input_capture(
            app, args.input_capture_save_dir,
            capture_indices=[int(i) for i in args.capture_indices],
        )

    import contextlib

    if args.profile_dir:
        from neuronx_distributed_inference_tpu.utils.profiling import profile_capture

        profile_ctx = profile_capture(args.profile_dir)
    else:
        profile_ctx = contextlib.nullcontext()

    with profile_ctx:
        if draft_app is not None:
            from neuronx_distributed_inference_tpu.runtime.assisted import assisted_generate

            out = assisted_generate(
                app, draft_app, input_ids, attention_mask,
                max_new_tokens=args.max_new_tokens, eos_token_id=eos_token_id,
                speculation_length=max(args.speculation_length, 2),
            )
        else:
            out = app.generate(input_ids, attention_mask, **gen_kwargs)
    if capture_hook is not None:
        print(f"[inference_demo] captured {len(capture_hook.saved)} input snapshots",
              file=sys.stderr)
    for i, seq in enumerate(out.sequences):
        text = tok.decode(seq, skip_special_tokens=True) if tok else seq.tolist()
        print(f"--- output {i} ---\n{text}")

    if args.check_accuracy_mode != "skip":
        from neuronx_distributed_inference_tpu.utils.accuracy import check_accuracy

        import transformers

        hf = transformers.AutoModelForCausalLM.from_pretrained(args.model_path).eval().float()
        capture_dir = None
        if args.input_capture_save_dir and (
            args.capture_indices == ["auto"] or not args.capture_indices
        ):
            # capture-on-divergence (reference --capture-indices auto,
            # inference_demo.py:600-614)
            capture_dir = args.input_capture_save_dir
        report = check_accuracy(
            app, input_ids, attention_mask, hf,
            max_new_tokens=args.max_new_tokens,
            divergence_tol=args.divergence_difference_tol,
            capture_dir=capture_dir,
        )
        print(f"[accuracy] passed={report.passed} {report.message}")
        if not report.passed:
            return 1

    if args.benchmark:
        from neuronx_distributed_inference_tpu.utils.benchmark import benchmark_sampling

        report = benchmark_sampling(
            app, input_ids, attention_mask,
            max_new_tokens=args.max_new_tokens, num_runs=args.num_runs,
            report_path="benchmark_report.json",
        )
        print(json.dumps(report, indent=2))
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return run_inference(args)


if __name__ == "__main__":
    sys.exit(main())
