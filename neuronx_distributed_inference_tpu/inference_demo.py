"""inference_demo-style CLI: compile / load / generate / accuracy / benchmark.

TPU-native re-design of the reference CLI
(reference: src/neuronx_distributed_inference/inference_demo.py — argparse
flags map 1:1 onto config fields :94-389; orchestration run_inference :458).

Usage:
    python -m neuronx_distributed_inference_tpu.inference_demo \
        --model-type llama --task-type causal-lm run \
        --model-path /path/to/hf/checkpoint \
        --compiled-model-path /tmp/compiled \
        --batch-size 1 --seq-len 1024 --tp-degree 1 \
        --prompt "I believe the meaning of life is" \
        --benchmark --check-accuracy-mode token-matching
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

from neuronx_distributed_inference_tpu.config import (
    InferenceConfig,
    OnDeviceSamplingConfig,
    TpuConfig,
)
from neuronx_distributed_inference_tpu.models.registry import MODEL_REGISTRY
from neuronx_distributed_inference_tpu.runtime.application import TpuModelForCausalLM
from neuronx_distributed_inference_tpu.utils.hf_adapter import load_pretrained_config


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="inference_demo", description=__doc__)
    p.add_argument("--model-type", default="llama", choices=sorted(MODEL_REGISTRY))
    p.add_argument("--task-type", default="causal-lm",
               choices=["causal-lm", "image-gen"])
    sub = p.add_subparsers(dest="action", required=True)
    run = sub.add_parser("run", help="compile, load, and generate")

    def onoff(name, default, dest=None, help=None):
        """--name / --no-name boolean pair (reference on/off flag pairs)."""
        dest = dest or name.replace("-", "_")
        run.add_argument(f"--{name}", dest=dest, action="store_true",
                         default=default, help=help)
        run.add_argument(f"--no-{name}", dest=dest, action="store_false")

    # paths
    # required for every mode except --workload-trace-out (which loads no
    # model) — enforced in main() so the trace generator runs standalone
    run.add_argument("--model-path", default=None)
    run.add_argument("--compiled-model-path", default=None)
    run.add_argument("--compilation-cache-dir", default=None)
    run.add_argument("--random-weights", action="store_true",
                     help="skip checkpoint load; random weights (perf/testing)")

    # core shapes (reference inference_demo.py:94-180)
    run.add_argument("--batch-size", type=int, default=1)
    run.add_argument("--max-batch-size", type=int, default=None)
    run.add_argument("--ctx-batch-size", type=int, default=None)
    run.add_argument("--tkg-batch-size", type=int, default=None)
    run.add_argument("--seq-len", type=int, default=1024)
    run.add_argument("--max-context-length", type=int, default=None)
    run.add_argument("--max-length", type=int, default=None)
    run.add_argument("--n-active-tokens", type=int, default=None)
    run.add_argument("--dtype", default="bfloat16",
                     choices=["bfloat16", "float32", "float16"])
    run.add_argument("--padding-side", default="right", choices=["right", "left"])
    onoff("cast-logits-fp32", True)
    onoff("attention-softmax-fp32", True)
    run.add_argument("--seed", type=int, default=0)
    onoff("async-mode", True, help="chained decode chunks, one sync per call")
    run.add_argument("--logical-nc-config", type=int, default=1)
    run.add_argument("--scratchpad-page-size", type=int, default=None)

    # parallelism (reference config.py:333-361)
    run.add_argument("--tp-degree", type=int, default=1)
    run.add_argument("--cp-degree", type=int, default=1)
    run.add_argument("--ep-degree", type=int, default=1)
    run.add_argument("--pp-degree", type=int, default=1)
    run.add_argument("--attention-dp-degree", type=int, default=1)
    run.add_argument("--data-parallel-degree", type=int, default=1,
                     help="whole-model DP over the leading ddp mesh axis")
    run.add_argument("--moe-tp-degree", type=int, default=None)
    run.add_argument("--moe-ep-degree", type=int, default=None)
    run.add_argument("--start-rank-id", type=int, default=0)
    run.add_argument("--local-ranks-size", type=int, default=None)
    run.add_argument("--sequence-parallel-enabled", action="store_true")
    run.add_argument("--vocab-parallel", action="store_true")
    run.add_argument("--flash-decoding-enabled", action="store_true")
    run.add_argument("--num-cores-per-group", type=int, default=1)

    # attention / kernels (reference ~25 kernel enable flags)
    run.add_argument("--fused-qkv", action="store_true")
    run.add_argument("--qk-norm", action="store_true")
    run.add_argument("--sliding-window", type=int, default=None)
    run.add_argument("--attention-chunk-size", type=int, default=None)
    run.add_argument("--attn-kernel-enabled", default=None,
                     type=lambda s: s.lower() in ("1", "true", "yes"),
                     help="flash prefill kernel: true/false (default: auto on TPU)")
    run.add_argument("--attn-block-tkg-kernel-enabled", default=None,
                     type=lambda s: s.lower() in ("1", "true", "yes"),
                     help="decode (TKG) attention kernel: true/false (default: auto)")
    run.add_argument("--attn-packed-kernel-enabled", default=None,
                     type=lambda s: s.lower() in ("1", "true", "yes"),
                     help="head-pair packed flash prefill for head_dim<=64: "
                          "true/false (default: auto-on on the flash path)")

    # bucketing
    onoff("enable-bucketing", True)
    run.add_argument("--context-encoding-buckets", type=int, nargs="+", default=None)
    run.add_argument("--token-generation-buckets", type=int, nargs="+", default=None)

    # KV cache / paged / serving (reference block-KV + chunked-prefill flags)
    from neuronx_distributed_inference_tpu.config import KV_CACHE_DTYPES

    run.add_argument(
        "--kv-cache-dtype", default=None, choices=list(KV_CACHE_DTYPES),
        help="KV cache storage dtype; int8/fp8 build the quantized cache "
        "(codes + per-(layer, head) scales, fused in-kernel dequant)",
    )
    run.add_argument("--kv-cache-batch-size", type=int, default=None)
    run.add_argument("--is-continuous-batching", action="store_true")
    run.add_argument("--is-block-kv-layout", action="store_true")
    run.add_argument("--pa-num-blocks", type=int, default=None)
    run.add_argument("--pa-block-size", type=int, default=16)
    run.add_argument(
        "--pa-pool-bytes", type=int, default=None,
        help="size the paged block pool by HBM bytes (block count derived "
        "from the cache dtype's true per-block cost; excludes pa-num-blocks)",
    )
    run.add_argument("--is-prefix-caching", action="store_true")
    run.add_argument("--is-chunked-prefill", action="store_true")
    run.add_argument(
        "--serving-ragged", action="store_true",
        help="ragged mixed-step serving dispatch: pack prefill chunks AND "
        "decode rows into ONE ragged paged-attention launch per step "
        "(requires --is-block-kv-layout under continuous batching; "
        "docs/SERVING.md)",
    )
    run.add_argument(
        "--serving-ragged-async", dest="serving_ragged_async",
        action="store_true", default=None,
        help="async 1-ahead pipelining for the ragged mixed-step path: "
        "step k+1 chains on step k's on-device tokens and the token fetch "
        "is non-blocking, overlapping host bookkeeping with the device "
        "(requires --serving-ragged; default follows async-mode)",
    )
    run.add_argument(
        "--no-serving-ragged-async", dest="serving_ragged_async",
        action="store_false",
        help="force dispatch+fetch-per-step on the ragged path "
        "(step-accurate debugging)",
    )
    run.add_argument(
        "--serving-spec-ragged", action="store_true",
        help="speculative verification inside the ragged mixed step "
        "(serving-session config consumed by drivers like bench.py's "
        "spec-ragged row — the demo itself runs one generate() session): "
        "spec rows carry draft tokens as extra packed query positions, one "
        "mixed dispatch per step serves prefill + decode + spec-verify rows "
        "(requires --serving-ragged, --is-chunked-prefill and "
        "2 <= --speculation-length <= 16; docs/SERVING.md)",
    )
    from neuronx_distributed_inference_tpu.config import ROUTER_POLICIES

    run.add_argument(
        "--serving-replicas", type=int, default=1,
        help="multi-replica router config (runtime/router.py, consumed by "
        "serving drivers like bench.py's router row — the demo itself runs "
        "one generate() session): how many single-chip replica sessions "
        "ServingRouter routes over; 1 = no router layer",
    )
    run.add_argument(
        "--router-policy", default="least_loaded",
        choices=list(ROUTER_POLICIES),
        help="replica placement policy for the router config above: "
        "least_loaded scores replicas from live telemetry (backlog, "
        "occupancy, kv_free_bytes, step/queue-wait EWMAs); cache_aware "
        "follows each replica's real prefix-cache match index (longest "
        "cached prefix wins, load order breaks ties)",
    )
    run.add_argument(
        "--router-prefill-replicas", type=int, default=0,
        help="disaggregated prefill tier (router config consumed by serving "
        "drivers like bench.py's disagg rows): carve this many of "
        "--serving-replicas out as dedicated prefill replicas feeding "
        "decode replicas over the contained KV hand-off; 0 = no tier "
        "(requires the contiguous cache; docs/SERVING.md)",
    )
    run.add_argument(
        "--handoff-max-retries", type=int, default=2,
        help="transient KV hand-off failures retried with capped backoff "
        "this many times; exhaustion fails only the in-flight request "
        "(FAILED(handoff)) and degrades the prefill replica",
    )
    run.add_argument(
        "--handoff-timeout-s", type=float, default=None,
        help="wall-clock bound for ONE hand-off attempt; an attempt past it "
        "counts as a failed attempt and retries (None disables)",
    )
    onoff("router-threading", False, dest="router_threading",
          help="thread-per-replica router stepping (router config consumed "
          "by serving drivers like bench.py's router rows): every alive "
          "replica's step() dispatches from a persistent worker pool and "
          "joins at a per-step barrier, so replica device steps overlap "
          "instead of host-serializing; placement/failover/telemetry stay "
          "on the router thread (docs/SERVING.md)")
    run.add_argument("--cp-max-num-seqs", type=int, default=8,
                     help="chunked prefill: max sequences per chunk batch")
    run.add_argument("--cp-kernel-q-tile-size", type=int, default=128)
    run.add_argument("--cp-kernel-kv-tile-size", type=int, default=512)

    # serving fault containment (runtime/serving.py; docs/SERVING.md)
    onoff("admission-validation", True, dest="admission_validation",
          help="typed REJECTED verdicts for malformed requests at admission "
          "(out-of-vocab ids, empty/over-long prompts, bad budgets) instead "
          "of raising mid-batch")
    run.add_argument(
        "--request-deadline-s", type=float, default=None,
        help="wall-clock TTL per request in seconds; past it the request is "
        "dropped with terminal reason deadline_exceeded",
    )
    run.add_argument(
        "--dispatch-max-retries", type=int, default=2,
        help="transient dispatch errors retried with capped backoff this "
        "many times; then only the in-flight rows fail",
    )
    run.add_argument(
        "--watchdog-no-progress-steps", type=int, default=256,
        help="serving steps with zero progress before the watchdog preempts "
        "the largest request (second window: loud WatchdogError); 0 disables",
    )

    # workload engine (workload/generator.py; docs/WORKLOADS.md): seeded
    # open-loop traffic generation. --workload-trace-out materializes the
    # reproducible arrival trace as JSON and exits WITHOUT loading a model
    # — the artifact replays through the WorkloadDriver / the bench
    # goodput rows (same seed => byte-identical trace, pinned).
    run.add_argument("--workload-seed", type=int, default=0,
                     help="workload trace seed (same seed => byte-identical "
                          "arrival trace)")
    run.add_argument("--workload-requests", type=int, default=32,
                     help="total arrivals in the generated trace")
    run.add_argument("--workload-arrival", default="poisson",
                     choices=["poisson", "onoff", "diurnal"],
                     help="arrival process: steady Poisson, bursty on/off, "
                          "or a diurnal rate envelope")
    run.add_argument("--workload-rate", type=float, default=1.0,
                     help="mean arrivals per virtual step (on-phase / peak "
                          "rate for onoff / diurnal)")
    run.add_argument("--workload-tenants", type=int, default=2,
                     help="tenant pools (alternating prose-ish/code-ish "
                          "spec-acceptance profiles, each with its own "
                          "shared prompt prefix)")
    run.add_argument("--workload-vocab", type=int, default=32000,
                     help="token-id range for the generated prompts (match "
                          "the serving model's vocab)")
    run.add_argument("--workload-max-prompt", type=int, default=128,
                     help="prompt-length upper bound (lognormal body is "
                          "clipped here — keep within the serving buckets)")
    run.add_argument("--workload-max-new-tokens", type=int, default=64,
                     help="output-budget upper bound (Zipf tail clipped)")
    run.add_argument("--workload-ttft-slo", type=float, default=None,
                     help="per-request TTFT SLO in virtual seconds (None "
                          "disables the TTFT term in goodput scoring)")
    run.add_argument("--workload-itl-slo", type=float, default=None,
                     help="per-request average-ITL SLO in virtual seconds")
    run.add_argument("--workload-trace-out", default=None,
                     help="write the generated arrival trace JSON here and "
                          "exit (no model load; replay via "
                          "workload.WorkloadTrace.loads + WorkloadDriver)")

    # sampling (reference on-device sampling flags)
    run.add_argument("--on-device-sampling", action="store_true")
    run.add_argument("--do-sample", action="store_true")
    run.add_argument("--top-k", type=int, default=1)
    run.add_argument("--top-p", type=float, default=1.0)
    run.add_argument("--temperature", type=float, default=1.0)
    run.add_argument("--global-topk", type=int, default=256)
    run.add_argument("--max-topk", type=int, default=256)
    run.add_argument("--deterministic", action="store_true")
    onoff("dynamic-sampling", True, dest="dynamic_sampling",
          help="per-request (top_k, top_p, temperature) tensors")
    run.add_argument("--output-logits", action="store_true")

    # quantization (reference --quantized*)
    run.add_argument("--quantized", action="store_true")
    run.add_argument("--quantization-type", default="per_channel_symmetric",
                     choices=["per_channel_symmetric", "per_tensor_symmetric",
                              "blockwise"])
    run.add_argument("--quantization-dtype", default="int8")
    run.add_argument("--quantized-checkpoints-path", default=None)
    # presharded weight artifact under <compiled_model_path>/presharded:
    # later runs restore sharded (possibly quantized) arrays directly — no
    # HF conversion, no quantize-at-load (reference save_sharded_checkpoint,
    # application_base.py:240-265; VERDICT r4 next #2 quantize-once)
    run.add_argument("--save-sharded-checkpoint", action="store_true")
    run.add_argument("--blockwise-matmul-block-size", type=int, default=128)
    run.add_argument("--modules-to-not-convert", nargs="+", default=None)

    # MoE (reference MoENeuronConfig flags)
    run.add_argument("--capacity-factor", type=float, default=None)
    run.add_argument("--router-dtype", default="float32")
    run.add_argument("--early-expert-affinity-modulation", action="store_true")
    onoff("normalize-top-k-affinities", True)
    run.add_argument("--hidden-act-scaling-factor", type=float, default=1.0)
    run.add_argument("--hidden-act-bias", type=float, default=0.0)
    onoff("glu-mlp", True)
    run.add_argument("--glu-type", default="glu")

    # LoRA multi-adapter serving (reference lora_serving flags)
    run.add_argument("--enable-lora", action="store_true")
    run.add_argument("--max-loras", type=int, default=1)
    run.add_argument("--max-lora-rank", type=int, default=16)
    run.add_argument("--max-loras-on-cpu", type=int, default=2)
    run.add_argument("--lora-ckpt-path", action="append", dest="lora_ckpt_paths",
                     default=None, metavar="NAME=PATH",
                     help="adapter checkpoint, repeatable: name=path")
    run.add_argument("--lora-dtype", default="bfloat16")
    run.add_argument("--lora-target-modules", nargs="+",
                     default=["q_proj", "k_proj", "v_proj", "o_proj"])
    run.add_argument("--adapter-id", action="append", dest="adapter_ids",
                     default=None, help="adapter name per prompt (repeatable)")

    # speculation (vanilla / fused / EAGLE / EAGLE3 / Medusa / token trees)
    run.add_argument("--draft-model-path", default=None)
    run.add_argument("--draft-model-type", default=None,
                     help="model_type of the draft (default: same as target; "
                          "llama-eagle / llama-eagle3 for EAGLE drafts)")
    run.add_argument("--speculation-length", type=int, default=0)
    run.add_argument("--enable-fused-speculation", action="store_true")
    run.add_argument("--enable-eagle-speculation", action="store_true")
    run.add_argument("--enable-eagle-draft-input-norm", action="store_true")
    run.add_argument("--is-eagle3", action="store_true",
                     help="EAGLE3: multi-layer target capture + 2H-qkv draft")
    run.add_argument("--token-tree-config", default=None,
                     help="token-tree JSON (inline or @file): adjacency dict "
                          "for static trees, or {step, branching_factor, "
                          "num_inputs} for dynamic trees")
    run.add_argument("--assisted-decoding", action="store_true",
                     help="vanilla (unfused) draft-assisted decoding: draft "
                          "and target compiled independently")
    run.add_argument("--is-medusa", action="store_true")
    run.add_argument("--medusa-speculation-length", type=int, default=0)
    run.add_argument("--num-medusa-heads", type=int, default=0)

    # generation
    run.add_argument("--prompt", action="append", dest="prompts", default=None)
    run.add_argument("--max-new-tokens", type=int, default=64)

    # eval
    run.add_argument("--benchmark", action="store_true")
    run.add_argument("--check-accuracy-mode", default="skip",
                     choices=["skip", "token-matching", "logit-matching"])
    run.add_argument("--divergence-difference-tol", type=float, default=0.001)
    run.add_argument("--num-runs", type=int, default=5)
    run.add_argument("--skip-warmup", action="store_true")

    # observability (reference inference_demo.py:329-334 + profiling)
    run.add_argument("--input-capture-save-dir", default=None,
                     help="directory for input snapshots / divergence capture")
    run.add_argument("--capture-indices", nargs="+", default=None,
                     help="dispatch indices to snapshot, or 'auto' to capture "
                          "only when the accuracy check diverges")
    run.add_argument("--profile-dir", default=None,
                     help="capture a jax.profiler device trace of generation "
                          "into this directory (view with tensorboard/XProf)")
    run.add_argument("--debug-io", action="store_true",
                     help="log every dispatch's input shapes and output tokens")
    run.add_argument("--capture-points", nargs="+", default=None,
                     help="tensor-capture tap points (modules/tensor_taps)")
    run.add_argument("--tensor-replacement-points", nargs="+", default=None,
                     help="tap points eligible for teacher forcing")
    run.add_argument("--metrics-out", default=None,
                     help="enable runtime telemetry and dump the JSON metrics "
                          "snapshot (bucket census, step counters, token "
                          "counts) to this path at exit; pretty-print with "
                          "scripts/metrics_report.py")
    run.add_argument("--trace-out", default=None,
                     help="enable runtime telemetry and export the run's "
                          "span timeline as Chrome trace-event JSON to this "
                          "path at exit (load in Perfetto / chrome://tracing; "
                          "docs/OBSERVABILITY.md)")
    run.add_argument("--ops-port", type=int, default=None,
                     help="serve the live ops surface (/metrics, /healthz, "
                          "/slo; docs/OBSERVABILITY.md) on this port for the "
                          "duration of the run (0 = ephemeral); the server "
                          "thread is joined on exit even if the run raises")
    return p


def _parse_token_tree(arg):
    if arg is None:
        return None
    if arg.startswith("@"):
        with open(arg[1:]) as f:
            return json.load(f)
    return json.loads(arg)


def create_tpu_config(args) -> TpuConfig:
    """CLI flags -> TpuConfig / MoETpuConfig (reference create_neuron_config,
    inference_demo.py:416-422)."""
    from neuronx_distributed_inference_tpu.config import (
        ChunkedPrefillConfig,
        LoraServingConfig,
        MoETpuConfig,
        TensorCaptureConfig,
        TensorReplacementConfig,
    )

    ods = None
    if args.on_device_sampling or args.do_sample:
        ods = OnDeviceSamplingConfig(
            do_sample=args.do_sample,
            top_k=args.top_k,
            top_p=args.top_p,
            temperature=args.temperature,
            dynamic=args.dynamic_sampling,
            global_topk=args.global_topk,
            deterministic=args.deterministic,
        )
    lora = None
    if args.enable_lora or args.lora_ckpt_paths:
        paths = dict(s.split("=", 1) for s in (args.lora_ckpt_paths or []))
        lora = LoraServingConfig(
            max_loras=args.max_loras,
            max_lora_rank=args.max_lora_rank,
            max_loras_on_cpu=args.max_loras_on_cpu,
            lora_ckpt_paths=paths or None,
            lora_dtype=args.lora_dtype,
            target_modules=tuple(args.lora_target_modules),
        )
    cpc = None
    if args.is_chunked_prefill:
        cpc = ChunkedPrefillConfig(
            max_num_seqs=args.cp_max_num_seqs,
            kernel_q_tile_size=args.cp_kernel_q_tile_size,
            kernel_kv_tile_size=args.cp_kernel_kv_tile_size,
        )
    kwargs = dict(
        batch_size=args.batch_size,
        max_batch_size=args.max_batch_size,
        ctx_batch_size=args.ctx_batch_size,
        tkg_batch_size=args.tkg_batch_size,
        seq_len=args.seq_len,
        max_context_length=args.max_context_length,
        max_length=args.max_length,
        n_active_tokens=args.n_active_tokens,
        dtype=args.dtype,
        padding_side=args.padding_side,
        cast_logits_fp32=args.cast_logits_fp32,
        attention_softmax_fp32=args.attention_softmax_fp32,
        seed=args.seed,
        async_mode=args.async_mode,
        logical_nc_config=args.logical_nc_config,
        scratchpad_page_size=args.scratchpad_page_size,
        compilation_cache_dir=args.compilation_cache_dir,
        save_sharded_checkpoint=args.save_sharded_checkpoint,
        tp_degree=args.tp_degree,
        cp_degree=args.cp_degree,
        ep_degree=args.ep_degree,
        pp_degree=args.pp_degree,
        attention_dp_degree=args.attention_dp_degree,
        data_parallel_degree=args.data_parallel_degree,
        moe_tp_degree=args.moe_tp_degree,
        moe_ep_degree=args.moe_ep_degree,
        start_rank_id=args.start_rank_id,
        local_ranks_size=args.local_ranks_size,
        sequence_parallel_enabled=args.sequence_parallel_enabled,
        vocab_parallel=args.vocab_parallel,
        flash_decoding_enabled=args.flash_decoding_enabled,
        num_cores_per_group=args.num_cores_per_group,
        fused_qkv=args.fused_qkv,
        qk_norm=args.qk_norm,
        sliding_window=args.sliding_window,
        attention_chunk_size=args.attention_chunk_size,
        attn_kernel_enabled=args.attn_kernel_enabled,
        attn_packed_kernel_enabled=args.attn_packed_kernel_enabled,
        attn_block_tkg_kernel_enabled=args.attn_block_tkg_kernel_enabled,
        enable_bucketing=args.enable_bucketing,
        context_encoding_buckets=args.context_encoding_buckets,
        token_generation_buckets=args.token_generation_buckets,
        kv_cache_dtype=args.kv_cache_dtype,
        kv_cache_batch_size=args.kv_cache_batch_size,
        is_continuous_batching=args.is_continuous_batching,
        is_block_kv_layout=args.is_block_kv_layout,
        pa_num_blocks=args.pa_num_blocks,
        pa_block_size=args.pa_block_size,
        pa_pool_bytes=args.pa_pool_bytes,
        is_prefix_caching=args.is_prefix_caching,
        is_chunked_prefill=args.is_chunked_prefill,
        chunked_prefill_config=cpc,
        serving_ragged=args.serving_ragged,
        serving_ragged_async=args.serving_ragged_async,
        serving_spec_ragged=args.serving_spec_ragged,
        serving_replicas=args.serving_replicas,
        router_policy=args.router_policy,
        router_threading=args.router_threading,
        router_prefill_replicas=args.router_prefill_replicas,
        handoff_max_retries=args.handoff_max_retries,
        handoff_timeout_s=args.handoff_timeout_s,
        admission_validation=args.admission_validation,
        request_deadline_s=args.request_deadline_s,
        dispatch_max_retries=args.dispatch_max_retries,
        watchdog_no_progress_steps=args.watchdog_no_progress_steps,
        on_device_sampling_config=ods,
        max_topk=args.max_topk,
        output_logits=args.output_logits
        or args.check_accuracy_mode == "logit-matching",
        quantized=args.quantized,
        quantization_type=args.quantization_type,
        quantization_dtype=args.quantization_dtype,
        quantized_checkpoints_path=args.quantized_checkpoints_path,
        blockwise_matmul_block_size=args.blockwise_matmul_block_size,
        modules_to_not_convert=args.modules_to_not_convert,
        lora_config=lora,
        speculation_length=args.speculation_length,
        enable_fused_speculation=args.enable_fused_speculation,
        enable_eagle_speculation=args.enable_eagle_speculation,
        enable_eagle_draft_input_norm=args.enable_eagle_draft_input_norm,
        is_eagle3=args.is_eagle3,
        token_tree_config=_parse_token_tree(args.token_tree_config),
        medusa_speculation_length=args.medusa_speculation_length,
        num_medusa_heads=args.num_medusa_heads,
        skip_warmup=args.skip_warmup,
        tensor_capture_config=(
            TensorCaptureConfig(points=args.capture_points)
            if args.capture_points else None
        ),
        tensor_replacement_config=(
            TensorReplacementConfig(points=args.tensor_replacement_points)
            if args.tensor_replacement_points else None
        ),
    )
    moe = (
        args.capacity_factor is not None
        or args.early_expert_affinity_modulation
        or args.router_dtype != "float32"
        or args.hidden_act_scaling_factor != 1.0
        or args.hidden_act_bias != 0.0
        or not args.normalize_top_k_affinities
        or not args.glu_mlp
        or args.glu_type != "glu"
    )
    if moe:
        return MoETpuConfig(
            capacity_factor=args.capacity_factor,
            router_dtype=args.router_dtype,
            early_expert_affinity_modulation=args.early_expert_affinity_modulation,
            normalize_top_k_affinities=args.normalize_top_k_affinities,
            hidden_act_scaling_factor=args.hidden_act_scaling_factor,
            hidden_act_bias=args.hidden_act_bias,
            glu_mlp=args.glu_mlp,
            glu_type=args.glu_type,
            **kwargs,
        )
    return TpuConfig(**kwargs)


def run_inference(args) -> int:
    """Orchestration (reference run_inference, inference_demo.py:458)."""
    from neuronx_distributed_inference_tpu.models.registry import get_model_builder

    tpu_config = create_tpu_config(args)
    builder_cls = get_model_builder(args.model_type)
    config_cls = getattr(builder_cls, "config_cls", InferenceConfig)
    load_config = load_pretrained_config(args.model_path)
    config = config_cls(tpu_config, load_config=load_config)

    if args.assisted_decoding and (
        args.enable_fused_speculation or args.enable_eagle_speculation
    ):
        raise ValueError(
            "--assisted-decoding is the unfused path; it conflicts with "
            "--enable-fused-speculation/--enable-eagle-speculation"
        )
    if args.assisted_decoding and args.do_sample:
        # sampled assisted decoding exists (runtime.assisted requires BOTH
        # apps loaded with do_sample on-device sampling + output_logits);
        # the demo doesn't build the draft app that way, so keep the gate
        raise NotImplementedError(
            "assisted decoding is greedy-only in inference_demo; sampled "
            "speculation runs through --enable-fused-speculation "
            "(multinomial accept/reject) or runtime.assisted directly"
        )
    fused_spec = args.enable_fused_speculation or args.enable_eagle_speculation or (
        args.draft_model_path and args.speculation_length >= 2
        and not args.assisted_decoding
    )
    assisted = args.assisted_decoding
    print(f"[inference_demo] building {args.model_type} app "
          f"(tp={args.tp_degree} ep={args.ep_degree} fused_spec={bool(fused_spec)} "
          f"eagle={args.enable_eagle_speculation} assisted={assisted})",
          file=sys.stderr)
    t0 = time.time()
    draft_app = None
    if args.is_medusa or args.medusa_speculation_length:
        from neuronx_distributed_inference_tpu.runtime.medusa import (
            TpuMedusaModelForCausalLM,
        )

        app = TpuMedusaModelForCausalLM(args.model_path, config)
        app.load(random_weights=args.random_weights)
    elif fused_spec:
        from neuronx_distributed_inference_tpu.config import FusedSpecConfig
        from neuronx_distributed_inference_tpu.runtime.fused_spec import (
            TpuEagleSpecModelForCausalLM,
            TpuFusedSpecModelForCausalLM,
        )

        if not args.draft_model_path:
            raise ValueError("fused/eagle speculation requires --draft-model-path")
        tpu_config.enable_fused_speculation = True
        tpu_config.enable_eagle_speculation = args.enable_eagle_speculation
        draft_type = args.draft_model_type or (
            ("llama-eagle3" if args.is_eagle3 else "llama-eagle")
            if args.enable_eagle_speculation
            else args.model_type
        )
        draft_builder_cls = get_model_builder(draft_type)
        draft_config_cls = getattr(draft_builder_cls, "config_cls", InferenceConfig)
        draft_config = draft_config_cls(
            create_tpu_config(args), load_config=load_pretrained_config(args.draft_model_path)
        )
        draft_config.model_type = draft_type
        config.fused_spec_config = FusedSpecConfig(
            draft_model_name=args.draft_model_path, draft_config=draft_config
        )
        app_cls = (
            TpuEagleSpecModelForCausalLM
            if args.enable_eagle_speculation
            else TpuFusedSpecModelForCausalLM
        )
        app = app_cls(args.model_path, config, draft_model_path=args.draft_model_path)
        app.load(random_weights=args.random_weights)
    else:
        app = TpuModelForCausalLM(args.model_path, config)
        # a presharded artifact makes the eager load redundant: compile()
        # restores the sharded (possibly quantized) arrays directly — no HF
        # conversion, no quantize-at-load (VERDICT r4 next #2; reference
        # save_sharded_checkpoint reload, application_base.py:240-265)
        # only skip the eager load for an artifact saved under THIS model +
        # quantization recipe — a stale or corrupt artifact must not
        # silently override the CLI flags (and must not crash: a kill
        # mid-write degrades to a normal load). One shared gate with
        # compile() so the checks cannot drift (utils/presharded.py).
        from neuronx_distributed_inference_tpu.utils.presharded import (
            artifact_ready,
        )

        artifact_ok = not args.random_weights and artifact_ready(
            config, args.compiled_model_path, args.model_path
        )
        if not artifact_ok:
            app.load(random_weights=args.random_weights)
        if args.lora_ckpt_paths:
            from neuronx_distributed_inference_tpu.utils.hf_checkpoint import (
                load_state_dict,
            )

            adapters = {}
            for entry in args.lora_ckpt_paths:
                name, path = entry.split("=", 1)
                adapters[name] = load_state_dict(path)
            app.load_lora_adapters(adapters)
        if assisted:
            if not args.draft_model_path:
                raise ValueError("--assisted-decoding requires --draft-model-path")
            draft_type = args.draft_model_type or args.model_type
            draft_builder_cls = get_model_builder(draft_type)
            draft_config_cls = getattr(draft_builder_cls, "config_cls", InferenceConfig)
            draft_config = draft_config_cls(
                create_tpu_config(args),
                load_config=load_pretrained_config(args.draft_model_path),
            )
            draft_config.model_type = draft_type
            draft_app = TpuModelForCausalLM(args.draft_model_path, draft_config)
            draft_app.load(random_weights=args.random_weights)
    print(f"[inference_demo] load: {time.time()-t0:.1f}s", file=sys.stderr)
    if not fused_spec:
        t0 = time.time()
        app.compile(args.compiled_model_path)
        print(f"[inference_demo] compile+warmup: {time.time()-t0:.1f}s", file=sys.stderr)

    # tokenize prompts
    prompts = args.prompts or ["I believe the meaning of life is"]
    try:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.model_path)
        enc = tok(prompts, return_tensors="np", padding=True, padding_side="right")
        input_ids = enc["input_ids"]
        attention_mask = enc["attention_mask"]
    except Exception as e:
        print(f"[inference_demo] tokenizer unavailable ({e}); using raw ids",
              file=sys.stderr)
        input_ids = np.array([[1] + [i % 100 + 2 for i in range(15)]] * len(prompts))
        attention_mask = np.ones_like(input_ids)
        tok = None

    eos_token_id = getattr(tok, "eos_token_id", None) if tok else None
    gen_kwargs = dict(max_new_tokens=args.max_new_tokens, eos_token_id=eos_token_id)
    if args.adapter_ids:
        gen_kwargs["lora_adapter_names"] = args.adapter_ids
    if args.do_sample:
        gen_kwargs.update(
            top_k=args.top_k, top_p=args.top_p, temperature=args.temperature
        )
    if args.debug_io:
        from neuronx_distributed_inference_tpu.utils.snapshot import enable_debug_logging

        enable_debug_logging()
    metrics_session = metrics_prev = None
    if args.metrics_out or args.trace_out:
        # a RUN-scoped session over a fresh registry (not the cumulative
        # process-default): the snapshot must describe THIS invocation, not
        # whatever else the embedding process ran earlier
        from neuronx_distributed_inference_tpu.telemetry import (
            TelemetrySession,
            tracing as _tel_tracing,
        )

        metrics_prev = _tel_tracing.default_session()
        metrics_session = _tel_tracing.set_default_session(TelemetrySession())
    capture_hook = None
    if args.input_capture_save_dir and args.capture_indices and args.capture_indices != ["auto"]:
        from neuronx_distributed_inference_tpu.utils.snapshot import install_input_capture

        capture_hook = install_input_capture(
            app, args.input_capture_save_dir,
            capture_indices=[int(i) for i in args.capture_indices],
        )

    import contextlib

    if args.profile_dir:
        from neuronx_distributed_inference_tpu.utils.profiling import profile_capture

        profile_ctx = profile_capture(args.profile_dir)
    else:
        profile_ctx = contextlib.nullcontext()

    # the ops HTTP surface rides the run as a CONTEXT MANAGER so its serve
    # thread is joined even when generation raises (LIFE804)
    if args.ops_port is not None:
        from neuronx_distributed_inference_tpu.telemetry import default_registry
        from neuronx_distributed_inference_tpu.telemetry.ops_server import OpsServer

        ops_ctx = OpsServer(
            (metrics_session.registry if metrics_session is not None
             else default_registry()),
            port=args.ops_port,
        )
    else:
        ops_ctx = contextlib.nullcontext()

    with ops_ctx as ops:
        if ops is not None:
            print(f"[inference_demo] ops server -> {ops.url}", file=sys.stderr)
        with profile_ctx:
            if draft_app is not None:
                from neuronx_distributed_inference_tpu.runtime.assisted import assisted_generate

                out = assisted_generate(
                    app, draft_app, input_ids, attention_mask,
                    max_new_tokens=args.max_new_tokens, eos_token_id=eos_token_id,
                    speculation_length=max(args.speculation_length, 2),
                )
            else:
                out = app.generate(input_ids, attention_mask, **gen_kwargs)
    if capture_hook is not None:
        print(f"[inference_demo] captured {len(capture_hook.saved)} input snapshots",
              file=sys.stderr)
    if metrics_session is not None:
        from neuronx_distributed_inference_tpu.telemetry import (
            tracing as _tel_tracing,
        )

        if args.metrics_out:
            with open(args.metrics_out, "w") as f:
                json.dump(metrics_session.registry.snapshot(), f, indent=2)
            print(f"[inference_demo] metrics snapshot -> {args.metrics_out}",
                  file=sys.stderr)
        if args.trace_out:
            metrics_session.export_chrome_trace(args.trace_out)
            print(f"[inference_demo] chrome trace -> {args.trace_out}",
                  file=sys.stderr)
        _tel_tracing.set_default_session(metrics_prev)
        metrics_session.close()
    for i, seq in enumerate(out.sequences):
        text = tok.decode(seq, skip_special_tokens=True) if tok else seq.tolist()
        print(f"--- output {i} ---\n{text}")

    if args.check_accuracy_mode != "skip":
        from neuronx_distributed_inference_tpu.utils.accuracy import check_accuracy

        import transformers

        hf = transformers.AutoModelForCausalLM.from_pretrained(args.model_path).eval().float()
        capture_dir = None
        if args.input_capture_save_dir and (
            args.capture_indices == ["auto"] or not args.capture_indices
        ):
            # capture-on-divergence (reference --capture-indices auto,
            # inference_demo.py:600-614)
            capture_dir = args.input_capture_save_dir
        report = check_accuracy(
            app, input_ids, attention_mask, hf,
            max_new_tokens=args.max_new_tokens,
            divergence_tol=args.divergence_difference_tol,
            capture_dir=capture_dir,
        )
        print(f"[accuracy] passed={report.passed} {report.message}")
        if not report.passed:
            return 1

    if args.benchmark:
        from neuronx_distributed_inference_tpu.utils.benchmark import benchmark_sampling

        report = benchmark_sampling(
            app, input_ids, attention_mask,
            max_new_tokens=args.max_new_tokens, num_runs=args.num_runs,
            report_path="benchmark_report.json",
        )
        print(json.dumps(report, indent=2))
    return 0


def run_image_gen(args) -> int:
    """FLUX text-to-image (reference NeuronFluxApplication demo path,
    models/diffusers/flux/application.py): random-weight smoke or checkpoint
    generation with the four-sub-model pipeline."""
    import numpy as np

    from neuronx_distributed_inference_tpu.models.flux import FluxSpec
    from neuronx_distributed_inference_tpu.models.flux_text import (
        ClipTextSpec,
        T5EncoderSpec,
    )
    from neuronx_distributed_inference_tpu.models.flux_vae import VaeDecoderSpec
    from neuronx_distributed_inference_tpu.runtime.flux import (
        FluxPipelineConfig,
        TpuFluxPipeline,
    )

    if not args.random_weights:
        raise NotImplementedError(
            "image-gen demo currently drives random-weight pipelines; load "
            "checkpoints through runtime.flux.TpuFluxPipeline.load(...)"
        )
    cfg = FluxPipelineConfig(
        backbone=FluxSpec(
            dim=128, num_heads=4, head_dim=32, num_dual=2, num_single=2,
            in_channels=64, joint_dim=64, pooled_dim=48,
            axes_dims_rope=(8, 12, 12),
        ),
        clip=ClipTextSpec(
            hidden_size=48, num_heads=4, num_layers=2, intermediate_size=96,
            vocab_size=1024, max_positions=77,
        ),
        t5=T5EncoderSpec(
            d_model=64, num_heads=4, d_kv=16, num_layers=2, d_ff=128,
            vocab_size=1024,
        ),
        vae=VaeDecoderSpec(latent_channels=16, block_out_channels=(32, 32, 32, 32)),
        height=128, width=128, dtype=args.dtype,
    )
    pipe = TpuFluxPipeline(cfg).load(random_weights=True, seed=args.seed)
    rng = np.random.RandomState(args.seed)
    clip_ids = rng.randint(1, 1000, size=(1, 8))
    t5_ids = rng.randint(1, 1000, size=(1, 16))
    img = pipe.generate(clip_ids, t5_ids, num_inference_steps=4, seed=args.seed)
    print(f"generated image batch: shape={img.shape}, "
          f"range=[{img.min():.3f}, {img.max():.3f}]")
    return 0


def run_workload_trace(args) -> int:
    """--workload-trace-out: materialize the seeded arrival trace and write
    it as JSON (no model load — trace generation is pure host data). The
    artifact is the reproducibility handle: archive it beside a bench
    goodput run and replay it bit-exactly later."""
    from neuronx_distributed_inference_tpu.workload import (
        generate,
        standard_spec,
    )

    trace = generate(standard_spec(
        seed=args.workload_seed,
        n_requests=args.workload_requests,
        vocab_size=args.workload_vocab,
        arrival_kind=args.workload_arrival,
        rate=args.workload_rate,
        n_tenants=args.workload_tenants,
        max_prompt_len=args.workload_max_prompt,
        max_output_len=args.workload_max_new_tokens,
        ttft_slo_s=args.workload_ttft_slo,
        itl_slo_s=args.workload_itl_slo,
        spec_profiles=True,
    ))
    with open(args.workload_trace_out, "w") as f:
        f.write(trace.dumps())
    print(
        f"workload trace -> {args.workload_trace_out} "
        f"({len(trace.arrivals)} arrivals, digest {trace.digest()[:16]})"
    )
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.workload_trace_out:
        return run_workload_trace(args)
    if args.model_path is None:
        print(
            "inference_demo: error: --model-path is required "
            "(it may be omitted only with --workload-trace-out, which "
            "loads no model)",
            file=sys.stderr,
        )
        return 2
    if args.task_type == "image-gen":
        return run_image_gen(args)
    return run_inference(args)


if __name__ == "__main__":
    sys.exit(main())
