"""Pallas paged (block-KV) flash attention for chunked / prefix prefill.

TPU-native re-design of the reference's schedule-driven paged flash kernel
(reference: modules/chunked_prefill/flash_pa_with_schedule.py:157 +
flash_attn_core.py:70, driven by the host GridTileScheduler,
scheduler.py:274-420).

Design: the reference builds an explicit host-side tile schedule because NKI
kernels address SBUF manually. On TPU the same thing falls out of the Pallas
grid + scalar-prefetch index maps: grid = (B, Hq, q_tiles, kv_blocks); the
KV BlockSpec's index_map reads the per-sequence ``block_table`` (a scalar
prefetch operand) to DMA the right cache block per grid step — no gather
materialization, no schedule arrays. Tiles that are entirely above the causal
frontier or beyond the sequence's populated length are skipped via
``pl.when`` on scalar-prefetched per-tile maxima (the scheduler's
skip-fully-masked-tiles optimization).

Numerics: online-softmax flash attention over the query's full prior context
(prefix blocks + causal among the new tokens) — the mask the native path
builds from masks.spec_token_gen_mask, fused into the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.tile_defaults import tile_default

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


# kernel/native dispatch gate: consolidated in ops/kernel_mode.py (one
# tested predicate per kernel); the historical name stays importable here
from neuronx_distributed_inference_tpu.ops.kernel_mode import (  # noqa: E402
    use_paged_flash as _use_paged_flash,
)


def _paged_kernel(
    # scalar prefetch
    block_table_ref,  # (B, MB) int32
    kv_limit_ref,  # (B,) int32 valid cache length per row
    tile_max_ref,  # (B, nq) int32 max q position per q tile
    # blocked operands
    q_ref,  # (1, 1, tq, D)
    pos_ref,  # (1, 1, tq) int32 q positions (dummy middle axis for Mosaic)
    k_ref,  # (1, 1, bs, D) one head's cache block
    v_ref,  # (1, 1, bs, D)
    o_ref,  # (1, 1, tq, D)
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    tq: int,
    bs: int,
    nkv: int,
):
    b = pl.program_id(0)
    iq = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kv_start = j * bs
    # skip tiles above the causal frontier or beyond the populated cache
    run = (kv_start <= tile_max_ref[b, iq]) & (kv_start < kv_limit_ref[b])

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # (tq, D)
        k = k_ref[0, 0].astype(jnp.float32)  # (bs, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (tq, bs)

        q_pos = pos_ref[0, 0]  # (tq,)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (tq, bs), 1)
        mask = (kv_pos <= q_pos[:, None]) & (kv_pos < kv_limit_ref[b])
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        # rows with no valid kv yet: m_new = NEG_INF -> p = exp(0) = 1;
        # zero them via the mask instead
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)

        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)  # (bs, D)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(j == nkv - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0, :, :] = (acc_scr[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "n_rep", "tq", "interpret")
)
def paged_flash_attention(
    q: jax.Array,  # (B, Sq, Hq, D)
    k_cache: jax.Array,  # (NB+1, Hkv, bs, D) one layer's head-major paged cache
    v_cache: jax.Array,
    block_table: jax.Array,  # (B, MB) int32
    positions: jax.Array,  # (B, Sq) int32 query positions
    kv_limit: jax.Array,  # (B,) int32 valid cache length per row
    *,
    scale: float,
    n_rep: int,
    tq: int = None,
    k_scale: jax.Array = None,  # (Hkv,) per-head dequant factor (scale/qmax)
    v_scale: jax.Array = None,  # for int8/fp8 caches; None = plain cache
    interpret: bool = False,
) -> jax.Array:
    """Prefix/chunked-prefill attention straight off the paged cache.

    Returns (B, Sq, Hq, D). Query token t of row b attends cache positions
    p <= positions[b, t] with p < kv_limit[b] — prior context plus causal
    among the new tokens (KV for the new tokens must already be written;
    write-then-attend as everywhere else).

    Quantized caches pass the raw int8/fp8 code blocks plus this layer's
    per-head dequant factors: the K factor folds into q (scaling the QKᵀ
    product), the V factor scales the per-head output after the online
    softmax — the kernel DMAs narrow code tiles, converts to fp32
    in-register, and never materializes a dequantized cache.
    """
    B, Sq, Hq, D = q.shape
    _, Hkv, bs, _ = k_cache.shape
    MB = block_table.shape[1]
    if tq is None:
        # q-tile default through the tuning table (KERN704), keyed by the
        # prefill chunk length and the cache dtype (int8 codes DMA narrower)
        tq = tile_default(
            "paged_flash_attention", f"sq{Sq}", k_cache.dtype, "tq", 128
        )
    tq = min(tq, Sq)
    nq = pl.cdiv(Sq, tq)

    out_dtype = q.dtype
    if k_scale is not None:
        q = q.astype(jnp.float32) * jnp.repeat(k_scale, n_rep)[None, None, :, None]
    qt = jnp.swapaxes(q, 1, 2)  # (B, Hq, Sq, D)
    # per-(row, q-tile) causal frontier for tile skipping
    pos_pad = jnp.pad(positions, ((0, 0), (0, nq * tq - Sq)))
    tile_max = jnp.max(pos_pad.reshape(B, nq, tq), axis=-1).astype(jnp.int32)

    kernel = functools.partial(
        _paged_kernel, scale=scale, tq=tq, bs=bs, nkv=MB
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Hq, nq, MB),
        in_specs=[
            pl.BlockSpec((1, 1, tq, D), lambda b, h, iq, j, bt, lim, tm: (b, h, iq, 0)),
            # dummy middle axis: block (1, tq) over a (B, Sq) array violates
            # Mosaic's (8, 128) last-two-dims rule for B > 1
            pl.BlockSpec((1, 1, tq), lambda b, h, iq, j, bt, lim, tm: (b, 0, iq)),
            # head-major cache: one head's block is a (bs, D) tile whose
            # last-two block dims equal the array dims
            pl.BlockSpec(
                (1, 1, bs, D),
                lambda b, h, iq, j, bt, lim, tm: (bt[b, j], h // n_rep, 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, bs, D),
                lambda b, h, iq, j, bt, lim, tm: (bt[b, j], h // n_rep, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, tq, D), lambda b, h, iq, j, bt, lim, tm: (b, h, iq, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((tq, 1), jnp.float32),
            pltpu.VMEM((tq, 1), jnp.float32),
            pltpu.VMEM((tq, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, nq * tq, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        block_table.astype(jnp.int32),
        kv_limit.astype(jnp.int32),
        tile_max,
        qt,
        positions.astype(jnp.int32)[:, None, :],
        k_cache,
        v_cache,
    )
    out = jnp.swapaxes(out, 1, 2)[:, :Sq]
    if v_scale is not None:
        out = (out * jnp.repeat(v_scale, n_rep)[None, None, :, None]).astype(out_dtype)
    return out
