"""int4 fused-dequant weight-streaming matmul (ISSUE 17 tentpole b).

Decode is weight-bandwidth-bound: after the int8 halving (PR 3) the next
step is sub-8-bit codes. This module owns the packed-int4 weight format and
the Pallas kernel that DMAs the narrow codes + per-group scales and
dequantizes IN REGISTER into the MXU matmul — the dequantized weight matrix
is never materialized in HBM or VMEM.

Packed layout (the format every int4 entry in a param tree uses)
----------------------------------------------------------------
A logical weight ``(..., K, N)`` quantizes symmetrically per
``(group, out_channel)`` with groups of :data:`INT4_GROUP` along the input
axis. K is zero-padded up to ``Kp``, the next multiple of ``2*group`` (pad
codes dequantize to exactly 0), then stored as

- ``weight``: uint8 ``(..., Kp/2, N)`` — **midpoint split**: byte row ``j``
  holds code ``k=j`` in the low nibble and code ``k=Kp/2+j`` in the high
  nibble. Unlike adjacent-pair interleave, both nibble planes are
  contiguous row ranges, so the kernel slices plain group blocks with no
  lane-strided shuffles, and ``Kp % 2*group == 0`` keeps every group inside
  one nibble plane.
- ``scale``: float32 ``(..., Kp/group, N)`` — groups ``0..Kp/2/group-1``
  cover the low plane, the rest the high plane.

Codes are ``q + 8`` with ``q = clip(round(w/s), -7, 7)`` — biased uint4 in
``[1, 15]``; 8 (= q 0) is the pad value. ``w ≈ (code - 8) * s``.

Kernel (``quant_matmul``)
-------------------------
Grid ``(N/bn,)`` over output tiles; the full (small, decode-sized) row
block and the full packed K stay resident per step. Each step unrolls the
group loop: a ``(rows, group) @ (group, bn)`` MXU dot per nibble plane per
group, scaled by that group's ``(bn,)`` scale row AFTER the dot — the exact
K-scale-folding convention of ``ops/decode_attention.py`` (codes through
the MXU, dequant factors applied outside the contraction). Group = 128
keeps every contraction MXU-full. The native fallback
(:func:`int4_matmul_native`) runs the same group-structured math with plain
einsums so every config serves on CPU and on GSPMD-sharded meshes
(pallas_call has no partitioning rule — the gate in ops/kernel_mode.py
keeps the kernel single-shard).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.tile_defaults import tile_default

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

#: default scale-group size along the input axis (= one MXU contraction)
INT4_GROUP = 128

#: symmetric 4-bit range: codes are q+8, q in [-7, 7] (the -8 code is unused
#: so the grid is symmetric and the pad byte 0x88 dequantizes to exactly 0)
INT4_QMAX = 7.0


def quantize_tensor_int4(w, group_size: int = INT4_GROUP):
    """Quantize ``(..., K, N)`` to the packed int4 entry
    ``{"weight": uint8 (..., Kp/2, N), "scale": f32 (..., Kp/group, N)}``.

    Numpy inputs quantize WITH numpy and return numpy (quantize-at-load must
    not stage fp32 on device — the ops/quant.py convention)."""
    xp = np if isinstance(w, np.ndarray) else jnp
    wf = w.astype(xp.float32)
    *lead, K, N = wf.shape
    span = 2 * group_size
    Kp = -(-K // span) * span
    if Kp != K:
        wf = xp.pad(wf, [(0, 0)] * len(lead) + [(0, Kp - K), (0, 0)])
    nG = Kp // group_size
    wg = wf.reshape(*lead, nG, group_size, N)
    absmax = xp.maximum(xp.max(xp.abs(wg), axis=-2), 1e-8)
    scale = (absmax / INT4_QMAX).astype(xp.float32)  # (..., nG, N)
    q = xp.clip(xp.round(wg / scale[..., None, :]), -INT4_QMAX, INT4_QMAX)
    codes = (q + 8).astype(xp.uint8).reshape(*lead, Kp, N)
    k2 = Kp // 2
    lo = codes[..., :k2, :]
    hi = codes[..., k2:, :]
    return {"weight": lo | (hi << 4), "scale": scale}


def is_int4_entry(entry) -> bool:
    """Packed-int4 discriminator: uint8 is structural — no other weight
    format in the tree stores uint8 codes (int8 weights are jnp.int8)."""
    return (
        isinstance(entry, dict)
        and "scale" in entry
        and "weight" in entry
        and jnp.dtype(entry["weight"].dtype) == jnp.uint8
    )


def dequantize_int4(packed, scale, k: int = None, dtype=jnp.float32):
    """Unpack ``(..., Kp/2, N)`` codes + ``(..., Kp/G, N)`` scales back to
    the logical ``(..., k, N)`` weight (trailing pad rows sliced off when
    ``k`` is given). Works on device arrays and numpy alike; leading dims
    (stacked layers / experts) pass through."""
    xp = np if isinstance(packed, np.ndarray) else jnp
    k2, n = packed.shape[-2], packed.shape[-1]
    kp = 2 * k2
    n_g = scale.shape[-2]
    group = kp // n_g
    codes = packed.astype(xp.int32)
    w = xp.concatenate(
        [(codes & 15) - 8, (codes >> 4) - 8], axis=-2
    ).astype(xp.float32)
    lead = w.shape[:-2]
    wg = w.reshape(*lead, n_g, group, n) * scale[..., None, :]
    w = wg.reshape(*lead, kp, n)
    if k is not None and k != kp:
        w = w[..., :k, :]
    return w.astype(dtype)


def maybe_dequantize_int4(entry, k: int, dtype):
    """Entry-level adapter for weight-consuming paths that don't speak the
    packed format (MoE expert einsums): packed entries come back as a plain
    dequantized entry (bias preserved), everything else passes through."""
    if not is_int4_entry(entry):
        return entry
    out = {"weight": dequantize_int4(entry["weight"], entry["scale"], k, dtype)}
    if "bias" in entry:
        out["bias"] = entry["bias"]
    return out


def int4_matmul_native(x, packed, scale):
    """Native fused-dequant matmul: the same group-structured math as the
    kernel (per-group code dot, scale applied after the dot, f32
    accumulation) as plain XLA ops — GSPMD-shardable, runs everywhere."""
    if packed.ndim != 2:
        raise ValueError(
            f"int4 matmul takes a 2D packed weight, got {packed.shape} "
            "(select the layer/expert before the matmul)"
        )
    k = x.shape[-1]
    k2, n = packed.shape
    kp = 2 * k2
    n_g = scale.shape[-2]
    group = kp // n_g
    codes = packed.astype(jnp.int32)
    w = jnp.concatenate(
        [(codes & 15) - 8, (codes >> 4) - 8], axis=-2
    ).astype(jnp.float32)
    xf = x.astype(jnp.float32)
    if kp != k:
        xf = jnp.pad(xf, [(0, 0)] * (x.ndim - 1) + [(0, kp - k)])
    xg = xf.reshape(*xf.shape[:-1], n_g, group)
    wg = w.reshape(n_g, group, n)
    y = jnp.einsum("...ng,ngo->...no", xg, wg)
    y = jnp.einsum("...no,no->...o", y, scale.astype(jnp.float32))
    return y.astype(x.dtype)


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, *, n_groups2: int, group: int, k2: int):
    """One (rows, bn) output tile: unrolled group loop over both nibble
    planes. Codes go through the MXU as small integers in f32; each group's
    scale row multiplies its partial product AFTER the dot (exact for the
    shared per-(group, out) factor)."""
    acc = jnp.zeros(o_ref.shape, jnp.float32)
    for g in range(n_groups2):
        codes = w_ref[g * group : (g + 1) * group, :].astype(jnp.int32)
        lo = ((codes & 15) - 8).astype(jnp.float32)
        hi = ((codes >> 4) - 8).astype(jnp.float32)
        x_lo = x_ref[:, g * group : (g + 1) * group].astype(jnp.float32)
        x_hi = x_ref[:, k2 + g * group : k2 + (g + 1) * group].astype(jnp.float32)
        acc += jax.lax.dot_general(
            x_lo, lo, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * s_ref[g, 0, :]
        acc += jax.lax.dot_general(
            x_hi, hi, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * s_ref[n_groups2 + g, 0, :]
    o_ref[:] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bn", "interpret"))
def quant_matmul(
    x: jax.Array,  # (..., K) activations (decode-sized leading dims)
    packed: jax.Array,  # (Kp/2, N) uint8 midpoint-split codes
    scale: jax.Array,  # (Kp/G, N) f32 per-(group, out) dequant factors
    *,
    bn: int = None,
    interpret: bool = False,
) -> jax.Array:
    """Fused-dequant ``x @ dequant(packed, scale)`` -> (..., N).

    The codes stream HBM->VMEM at 0.5 byte/param (+ ~1.6% scales) — the
    bandwidth the decode roofline actually pays. ``bn`` defaults through the
    tuning table (KERN704, kernel ``quant_matmul``)."""
    k = x.shape[-1]
    k2, n = packed.shape
    kp = 2 * k2
    n_g = scale.shape[0]
    if scale.shape != (n_g, n):
        raise ValueError(f"scale {scale.shape} does not match weight (*, {n})")
    if kp % n_g or (kp // n_g) % 2 or k2 % (kp // n_g):
        raise ValueError(
            f"packed K {kp} is not an even multiple of the group count {n_g}"
        )
    group = kp // n_g
    if n % 128:
        raise ValueError(f"output width {n} must be lane-aligned (128)")
    if bn is None:
        bn = tile_default(
            "quant_matmul", f"k{kp}_n{n}", x.dtype, "bn", 256
        )
    bn = min(bn, n)
    while n % bn:
        bn //= 2
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= d
    x2 = x.reshape(rows, k)
    if kp != k:
        x2 = jnp.pad(x2, [(0, 0), (0, kp - k)])

    kernel = functools.partial(
        _qmm_kernel, n_groups2=k2 // group, group=group, k2=k2
    )
    out = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((rows, kp), lambda j: (0, 0)),
            pl.BlockSpec((k2, bn), lambda j: (0, j)),
            pl.BlockSpec((n_g, 1, bn), lambda j: (0, 0, j)),
        ],
        out_specs=pl.BlockSpec((rows, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(x2, packed, scale.reshape(n_g, 1, n))
    return out.reshape(*lead, n)
