"""Pallas ragged paged attention: one kernel for mixed prefill+decode batches.

The serving path historically dispatched separate context-encoding and
token-generation programs per step and interleaved them on the host.
Following *Ragged Paged Attention: A High-Performance and Flexible LLM
Inference Kernel for TPU* (PAPERS.md), this kernel processes a RAGGED batch
against the paged KV cache in a single launch: each row is described by
``(query_start, query_len, context_len)`` — prefill chunks carry
``query_len > 1``, decode rows ``query_len == 1`` — and all rows' query
tokens are packed along one axis.

Packing contract (enforced by the host packer, ``MixedStepRunner.prepare``):

- every row's ``query_start`` is a multiple of :data:`RAGGED_Q_TILE`, so one
  q tile never spans two rows (the grid maps tile -> row via a scalar-
  prefetched ``tile_row`` table instead of the full per-token search the
  reference kernel does in its DMA schedule);
- padded slots between segments carry position ``-1`` (masked out of the
  softmax, their cache writes dropped via slot ``-1``);
- SPEC-VERIFY rows (serving_spec_ragged) are decode rows with
  ``query_len == draft_len + 1``: the segment carries the last committed
  token plus the draft chain at consecutive positions, and the per-token
  ``kv_pos <= q_pos`` causal mask over prior context + the in-flight
  segment is EXACTLY target verification of the candidate sequence — the
  kernel needs no spec-specific path, only segments wider than one token.
  ``draft_len`` must stay < :data:`RAGGED_Q_TILE` so a spec segment, like a
  plain decode row, occupies one q tile (config validation fences
  ``speculation_length <= RAGGED_Q_TILE``); the per-row draft length lives
  in the mixed program's ``verify_len`` descriptor
  (models/base.MixedStepInputs), not in this kernel's scalar prefetch — the
  attention math is draft-length-blind by construction.

Grid: ``(Hq, q_tiles, kv_blocks)`` — the KV BlockSpec index map reads the
per-row ``block_table`` through ``tile_row`` to DMA the right cache block
per step (no gather materialization); tiles above the causal frontier or
beyond a row's populated length are skipped via ``pl.when`` on scalar-
prefetched per-tile maxima, exactly like ``ops/paged_flash_attention.py``.

Quantized caches reuse the int8/fp8 code/scale convention of the paged
flash kernel: the K dequant factor folds into q before the launch (scaling
QK^T exactly), the V factor multiplies the per-head output after the online
softmax — narrow code tiles are DMA'd and converted in-register; no
dequantized cache is ever materialized.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.tile_defaults import tile_default

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30

#: q-tile granularity of the packed layout: row starts must align to it (a
#: tile belongs to exactly ONE row). 16 keeps bf16 q tiles Mosaic-friendly
#: ((16, 128) native tiling); a decode row therefore occupies one mostly-
#: padded 16-slot tile — masked VPU work, not extra KV DMA, and far less
#: waste than the per-phase full-batch padding the split dispatch paid.
RAGGED_Q_TILE = 16


def _use_ragged_kernel(spec, total_q: int) -> bool:
    """Kernel/native gate for the ragged mixed-step attention — consolidated
    in ops/kernel_mode.py. NO single-shard condition: tp>1 meshes dispatch
    the kernel per-shard via shard_map (see :func:`ragged_attention`)."""
    from neuronx_distributed_inference_tpu.ops.kernel_mode import use_ragged

    return use_ragged(spec, total_q, RAGGED_Q_TILE)


def _ragged_kernel(
    # scalar prefetch
    tile_row_ref,  # (NT,) int32 owning row per q tile
    tile_max_ref,  # (NT,) int32 max absolute q position per tile (-1 = pad)
    row_start_ref,  # (R,) int32 packed offset per row
    row_len_ref,  # (R,) int32 query tokens per row
    ctx_len_ref,  # (R,) int32 total kv length per row (incl. new tokens)
    block_table_ref,  # (R, MB) int32
    # blocked operands
    q_ref,  # (1, tq, D) one head's q tile
    k_ref,  # (1, 1, bs, D) one head's cache block
    v_ref,  # (1, 1, bs, D)
    o_ref,  # (1, tq, D)
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    tq: int,
    bs: int,
    nkv: int,
):
    t = pl.program_id(1)
    j = pl.program_id(2)
    r = tile_row_ref[t]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kv_start = j * bs
    # skip blocks above the tile's causal frontier or beyond the row's
    # populated cache (padded tiles carry tile_max == -1: nothing runs)
    run = (kv_start <= tile_max_ref[t]) & (kv_start < ctx_len_ref[r])

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # (tq, D)
        k = k_ref[0, 0].astype(jnp.float32)  # (bs, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (tq, bs)

        # per-token absolute position from the scalar descriptors alone (the
        # packed-positions array would need a Mosaic-hostile (1, tq) block):
        # in-row offset of packed slot t*tq+i, then position = the row's
        # first new-token position + offset; offsets past row_len are pad
        offs = (
            t * tq
            + jax.lax.broadcasted_iota(jnp.int32, (tq, bs), 0)
            - row_start_ref[r]
        )
        q_pos = (ctx_len_ref[r] - row_len_ref[r]) + offs
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (tq, bs), 1)
        mask = (
            (kv_pos <= q_pos)
            & (kv_pos < ctx_len_ref[r])
            & (offs < row_len_ref[r])
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        # fully-masked rows: m_new = NEG_INF -> exp(0) = 1; zero via the mask
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)

        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)  # (bs, D)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(j == nkv - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, :, :] = (acc_scr[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "n_rep", "tq", "interpret")
)
def ragged_paged_attention(
    q: jax.Array,  # (T, Hq, D) packed queries, row starts tq-aligned
    k_cache: jax.Array,  # (NB+1, Hkv, bs, D) one layer's head-major paged cache
    v_cache: jax.Array,
    block_table: jax.Array,  # (R, MB) int32
    row_start: jax.Array,  # (R,) int32 packed offset of each row's segment
    row_len: jax.Array,  # (R,) int32 query tokens per row (0 = inactive)
    ctx_len: jax.Array,  # (R,) int32 total kv length per row (incl. new)
    *,
    scale: float,
    n_rep: int,
    tq: int = None,
    k_scale: jax.Array = None,  # (Hkv,) per-head dequant factor (scale/qmax)
    v_scale: jax.Array = None,  # for int8/fp8 caches; None = plain cache
    interpret: bool = False,
) -> jax.Array:
    """One launch of mixed prefill-chunk + decode attention off the paged
    cache. Returns (T, Hq, D): the i-th query token of row r sits at
    absolute position ``ctx_len[r] - row_len[r] + i`` and attends cache
    positions p <= its own with p < ctx_len[r] — prior context plus causal
    among the new tokens (write-then-attend as everywhere else). Everything
    the kernel needs rides the scalar-prefetched descriptors; there is no
    per-token operand besides q itself.
    """
    T, Hq, D = q.shape
    _, Hkv, bs, _ = k_cache.shape
    R, MB = block_table.shape
    if tq is None:
        # default through the tuning table (KERN704). The packing contract
        # pins tq to a divisor of RAGGED_Q_TILE (row starts are
        # RAGGED_Q_TILE-aligned, so any divisor tile never spans rows);
        # KERN702 checks the committed entry against that arithmetic.
        tq = tile_default(
            "ragged_paged_attention", "mixed", k_cache.dtype, "tq", RAGGED_Q_TILE
        )
    if T % tq:
        raise ValueError(f"packed q length {T} not a multiple of tq={tq}")
    NT = T // tq

    out_dtype = q.dtype
    if k_scale is not None:
        q = q.astype(jnp.float32) * jnp.repeat(k_scale, n_rep)[None, :, None]
    qt = jnp.swapaxes(q, 0, 1)  # (Hq, T, D)

    row_start = row_start.astype(jnp.int32)
    row_len = row_len.astype(jnp.int32)
    ctx_len = ctx_len.astype(jnp.int32)
    # tile -> owning row (starts are tq-aligned so each tile has exactly one;
    # tiles past every row keep 0 and are skipped via tile_max == -1)
    t0 = jnp.arange(NT, dtype=jnp.int32) * tq
    hits = (t0[:, None] >= row_start[None, :]) & (
        t0[:, None] < (row_start + row_len)[None, :]
    )
    tile_row = jnp.argmax(hits, axis=1).astype(jnp.int32)
    # per-tile causal frontier: the highest absolute position among the
    # tile's valid tokens; -1 marks a fully-padded tile (nothing runs)
    last_off = jnp.minimum(
        jnp.take(row_len, tile_row) - 1,
        t0 + tq - 1 - jnp.take(row_start, tile_row),
    )
    ctx_first = jnp.take(ctx_len, tile_row) - jnp.take(row_len, tile_row)
    tile_max = jnp.where(
        jnp.any(hits, axis=1), ctx_first + last_off, -1
    ).astype(jnp.int32)

    kernel = functools.partial(
        _ragged_kernel, scale=scale, tq=tq, bs=bs, nkv=MB
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(Hq, NT, MB),
        in_specs=[
            pl.BlockSpec(
                (1, tq, D), lambda h, t, j, tr, tm, rs, rl, cl, bt: (h, t, 0)
            ),
            # head-major cache: one head's block is a (bs, D) tile addressed
            # through the OWNING ROW's block table
            pl.BlockSpec(
                (1, 1, bs, D),
                lambda h, t, j, tr, tm, rs, rl, cl, bt: (
                    bt[tr[t], j], h // n_rep, 0, 0,
                ),
            ),
            pl.BlockSpec(
                (1, 1, bs, D),
                lambda h, t, j, tr, tm, rs, rl, cl, bt: (
                    bt[tr[t], j], h // n_rep, 0, 0,
                ),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, tq, D), lambda h, t, j, tr, tm, rs, rl, cl, bt: (h, t, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((tq, 1), jnp.float32),
            pltpu.VMEM((tq, 1), jnp.float32),
            pltpu.VMEM((tq, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Hq, T, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        tile_row,
        tile_max,
        row_start,
        row_len,
        ctx_len,
        block_table.astype(jnp.int32),
        qt,
        k_cache,
        v_cache,
    )
    out = jnp.swapaxes(out, 0, 1)  # (T, Hq, D)
    if v_scale is not None:
        out = (out * jnp.repeat(v_scale, n_rep)[None, :, None]).astype(out_dtype)
    return out


def _dispatch_ragged_kernel(
    q3, k_l, v_l, block_table, row_start, row_len, ctx_len,
    *, scale, n_rep, k_scale, v_scale, interpret,
):
    """Launch the ragged kernel, per-shard over the model-parallel axes when
    the ambient mesh has any (ISSUE 17 tentpole a).

    The kernel grid is already head-parallel: q's head axis and the paged
    cache's kv-head axis are sharded over the model group (the same
    ``sharding.TENSOR`` axes the projection weights use), the descriptors
    (block table, row start/len, context lengths) are replicated host
    metadata, and GQA replication (parallel/sharding.GQASharding) guarantees
    both head counts divide the degree — so ``shard_map`` runs the identical
    per-head math on each shard with NO cross-shard collectives inside, and
    the tp>1 stream stays byte-identical to tp=1 and to the native fallback
    (pinned in tests/test_ragged_tp.py)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from neuronx_distributed_inference_tpu.parallel.mesh import (
        ALL_AXES,
        ambient_mesh,
    )

    mesh = ambient_mesh()
    axes = tuple(a for a in ALL_AXES if mesh is not None and a in mesh.shape)
    degree = 1
    for a in axes:
        degree *= mesh.shape[a]
    if degree == 1:
        return ragged_paged_attention(
            q3, k_l, v_l, block_table, row_start, row_len, ctx_len,
            scale=scale, n_rep=n_rep, k_scale=k_scale, v_scale=v_scale,
            interpret=interpret,
        )

    head = P(None, axes, None)
    args = [q3, k_l, v_l, block_table, row_start, row_len, ctx_len]
    in_specs = [head, P(None, axes, None, None), P(None, axes, None, None),
                P(), P(), P(), P()]
    if k_scale is not None:
        args += [k_scale, v_scale]
        in_specs += [P(axes), P(axes)]

    def per_shard(q_s, k_s, v_s, bt, rs, rl, cl, *scales):
        ks_s, vs_s = scales if scales else (None, None)
        return ragged_paged_attention(
            q_s, k_s, v_s, bt, rs, rl, cl,
            scale=scale, n_rep=n_rep, k_scale=ks_s, v_scale=vs_s,
            interpret=interpret,
        )

    return shard_map(
        per_shard, mesh=mesh, in_specs=tuple(in_specs), out_specs=head,
        check_rep=False,
    )(*args)


def ragged_attention_native(
    q: jax.Array,  # (T, Hq, D)
    k_cache,  # full stacked paged cache (L, NB+1, Hkv, bs, D) or QuantizedKV
    v_cache,
    layer_idx: jax.Array,
    block_table: jax.Array,  # (R, MB)
    positions: jax.Array,  # (T,)
    row_start: jax.Array,  # (R,)
    row_len: jax.Array,  # (R,)
    ctx_len: jax.Array,  # (R,)
    aspec,
) -> jax.Array:
    """Native reference/fallback: gather each row's blocks into a contiguous
    view (dequantizing quantized codes after the gather, like every native
    paged path), route each packed token to its row, and run the standard
    masked-softmax attention with the token axis as the batch — the exact
    math the legacy split dispatch runs, so greedy serving outputs are
    byte-identical across the dispatch modes."""
    from neuronx_distributed_inference_tpu.modules.attention import (
        attention_decode,
    )
    from neuronx_distributed_inference_tpu.modules.block_kvcache import (
        read_block_cache_at_layer,
    )

    T = q.shape[0]
    k_r, v_r = read_block_cache_at_layer(k_cache, v_cache, layer_idx, block_table)
    W = k_r.shape[1]
    tok = jnp.arange(T, dtype=jnp.int32)
    hits = (tok[:, None] >= row_start[None, :]) & (
        tok[:, None] < (row_start + row_len)[None, :]
    )
    row_id = jnp.argmax(hits, axis=1)  # (T,) 0 for padded slots (masked below)
    k_tok = jnp.take(k_r, row_id, axis=0)  # (T, W, Hkv, D)
    v_tok = jnp.take(v_r, row_id, axis=0)
    cols = jnp.arange(W, dtype=jnp.int32)[None, None, None, :]
    qpos = positions[:, None, None, None]
    mask = (
        (cols <= qpos)
        & (cols < jnp.take(ctx_len, row_id)[:, None, None, None])
        & (qpos >= 0)
    )  # (T, 1, 1, W)
    out = attention_decode(q[:, None], k_tok, v_tok, mask, aspec)
    return out[:, 0]


def ragged_attention(
    q: jax.Array,  # (1, T, Hq, D) — the mixed step's batch-1 packed layout
    k_cache,  # full stacked paged cache (or QuantizedKV stream)
    v_cache,
    layer_idx: jax.Array,
    block_table: jax.Array,
    positions: jax.Array,  # (1, T)
    row_start: jax.Array,
    row_len: jax.Array,
    ctx_len: jax.Array,
    aspec,
    *,
    interpret: bool = False,
) -> jax.Array:
    """Layer-level dispatch for the mixed-step program: the Pallas ragged
    kernel when eligible (DMA'ing this layer's raw code blocks with fused
    dequant for quantized caches), else the native gather fallback so every
    config runs on CPU. Returns (1, T, Hq, D)."""
    from neuronx_distributed_inference_tpu.modules.kvcache import (
        QuantizedKV,
        layer_dequant_factors,
    )

    q3 = q[0]
    T = q3.shape[0]
    if _use_ragged_kernel(aspec, T):
        ks = vs = None
        if isinstance(k_cache, QuantizedKV):
            ks = layer_dequant_factors(k_cache, layer_idx)
            vs = layer_dequant_factors(v_cache, layer_idx)
            k_arr, v_arr = k_cache.data, v_cache.data
        else:
            k_arr, v_arr = k_cache, v_cache
        k_l = jax.lax.dynamic_index_in_dim(k_arr, layer_idx, axis=0, keepdims=False)
        v_l = jax.lax.dynamic_index_in_dim(v_arr, layer_idx, axis=0, keepdims=False)
        out = _dispatch_ragged_kernel(
            q3, k_l, v_l, block_table, row_start, row_len, ctx_len,
            scale=aspec.softmax_scale,
            n_rep=aspec.num_heads // aspec.num_kv_heads,
            k_scale=ks, v_scale=vs,
            interpret=interpret,
        )
    else:
        out = ragged_attention_native(
            q3, k_cache, v_cache, layer_idx, block_table, positions[0],
            row_start, row_len, ctx_len, aspec,
        )
    return out[None]
