"""Pallas decode-attention (TKG) kernels — contiguous and paged caches.

TPU-native re-design of the reference's token-generation attention kernels
(reference: modules/attention/attention_base.py:1467 plain TKG NKI kernel,
:1531 builtin ISA kernel, :1609 attention_block_tokengen "mega" kernel for
the block cache).

Why a kernel at all: decode q_len is tiny (1..spec_len), so the native path's
``read_*_cache_at_layer`` + ``repeat_kv`` materializes a (B, S_kv, Hq, D)
gathered/broadcast view in HBM before the softmax — for the paged cache that
is a full gather of every active block per layer per step. These kernels DMA
cache tiles straight out of the FULL stacked cache (layer index and block
table ride scalar prefetch), with the decode mask fused in — nothing is
materialized.

Grid layout: (B, kv_tiles). Each step DMAs one (bs, Hkv, D) cache tile — all
KV heads at once, so the last two block dims stay full-size for Mosaic — and
an unrolled loop over the Hkv head groups runs the online softmax for that
group's n_rep*K query rows (GQA needs NO repeat_kv: queries are pre-grouped
rep-major). The cache is read exactly once, in tile-sized DMAs.

Masking is taken from the SAME (B, 1, K, S_kv) boolean mask the native path
uses — window/chunk/speculation decode masks all work unchanged — re-tiled to
(B, kv_tiles, K, bs), plus per-(row, tile) any() maxima as scalar prefetch so
fully-masked tiles are skipped (the causal-frontier skip of the reference
kernels).

Learned attention sinks (GPT-OSS) join the softmax denominator at finalize
(reference attention_base.py:1964-1980).

Quantized (int8/fp8) caches: both kernels take the :class:`QuantizedKV`
streams directly and DMA the NARROW code tiles — half (or a quarter of) the
bf16 bytes, which is the entire win on the bandwidth-bound decode step. The
per-(layer, head) symmetric scale is applied exactly, without materializing
a dequantized cache anywhere: the K scale folds into q before the kernel
(scaling the QKᵀ product — the online-softmax stats then run on true
scores), and the V scale multiplies the per-head output after finalize
(linear in the PV accumulation). In-kernel the codes convert to fp32
in-register (``.astype`` in ``_body``); stats/accumulators stay fp32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.tile_defaults import tile_default

from neuronx_distributed_inference_tpu.modules.kvcache import (
    QuantizedKV,
    layer_dequant_factors,
)

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


# kernel/native dispatch gate: consolidated in ops/kernel_mode.py (one
# tested predicate per kernel); the historical name stays importable here
from neuronx_distributed_inference_tpu.ops.kernel_mode import (  # noqa: E402
    use_tkg as use_tkg_kernel,
)


def _body(
    q_ref, mask_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
    *, scale, n_kv, rk, K, head_major=False,
):
    """One cache tile: unrolled loop over the Hkv head groups.

    ``head_major`` selects the cache tile layout: (Hkv, bs, D) for the paged
    cache (head-major blocks, see block_kvcache), (bs, Hkv, D) contiguous."""
    k_all = k_ref[0, 0].astype(jnp.float32)
    v_all = v_ref[0, 0].astype(jnp.float32)
    mt = mask_ref[0, 0] > 0  # (K, bs)
    bs = k_all.shape[1] if head_major else k_all.shape[0]
    row_mask = jnp.repeat(mt[None], rk // K, axis=0).reshape(rk, bs)
    for g in range(n_kv):
        rows = slice(g * rk, (g + 1) * rk)
        q = q_ref[0, rows, :].astype(jnp.float32)  # (rk, D)
        k = k_all[g] if head_major else k_all[:, g, :]  # (bs, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (rk, bs)
        s = jnp.where(row_mask, s, NEG_INF)

        m_prev = m_scr[rows, :]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(row_mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[rows, :] = l_scr[rows, :] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_all[g] if head_major else v_all[:, g, :]
        acc_scr[rows, :] = acc_scr[rows, :] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[rows, :] = m_new


def _finalize(o_ref, m_scr, l_scr, acc_scr, sink_ref, all_rows, K):
    if sink_ref is None:
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
    else:
        # sink logit joins the denominator (reference attention_base.py:1964):
        # renormalize both accumulators to m2 = max(m, sink) so rows that saw
        # no valid kv (m == -inf) stay finite and output zeros
        sink = sink_ref[0].astype(jnp.float32)  # (Hq,) row-major per head
        sink_row = jnp.repeat(sink[:, None], K, axis=1).reshape(all_rows, 1)
        m2 = jnp.maximum(m_scr[:], sink_row)
        alpha = jnp.exp(m_scr[:] - m2)
        denom = l_scr[:] * alpha + jnp.exp(sink_row - m2)
        o_ref[0] = (acc_scr[:] * alpha / denom).astype(o_ref.dtype)


def _tkg_kernel(*args, scale, n_kv, rk, K, nkv, has_sink, n_prefetch, head_major=False):
    prefetch, rest = args[:n_prefetch], args[n_prefetch:]
    tile_any_ref = prefetch[-1]
    if has_sink:
        q_ref, mask_ref, sink_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        q_ref, mask_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = rest
        sink_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(tile_any_ref[b, j] > 0)
    def _compute():
        _body(
            q_ref, mask_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
            scale=scale, n_kv=n_kv, rk=rk, K=K, head_major=head_major,
        )

    @pl.when(j == nkv - 1)
    def _fin():
        _finalize(o_ref, m_scr, l_scr, acc_scr, sink_ref, n_kv * rk, K)


def _prep_q(q: jax.Array):
    """(B, K, Hq, D) -> (B, Hq*K, D): row h*K + t. Head h's kv group is
    h // n_rep, so group g's rows are the contiguous [g*n_rep*K, (g+1)*n_rep*K)
    slice — the repeat_kv pairing with no broadcast."""
    B, K, Hq, D = q.shape
    return q.transpose(0, 2, 1, 3).reshape(B, Hq * K, D)


def _fold_k_dequant(q: jax.Array, k_cache: QuantizedKV, layer_idx, n_rep: int):
    """Fold the K stream's per-head dequant factor into q (fp32): the QKᵀ
    product then equals q·k̂ exactly, so mask/max/exp see true scores."""
    ks = layer_dequant_factors(k_cache, layer_idx)  # (Hkv,)
    return q.astype(jnp.float32) * jnp.repeat(ks, n_rep)[None, None, :, None]


def _apply_v_dequant(out: jax.Array, v_cache: QuantizedKV, layer_idx, n_rep: int):
    """Scale the per-head output by the V dequant factor: the accumulated
    Σ p·v_codes times scale/qmax equals Σ p·v̂ (scale constant per head)."""
    vs = layer_dequant_factors(v_cache, layer_idx)  # (Hkv,)
    return out * jnp.repeat(vs, n_rep)[None, None, :, None]


def _unprep_out(out: jax.Array, B: int, K: int, Hq: int, D: int):
    return out.reshape(B, Hq, K, D).transpose(0, 2, 1, 3)


def _mask_tiles(mask: jax.Array, nkv: int, bs: int):
    """(B, 1, K, S_kv) bool -> ((B, nkv, K, bs) int32, (B, nkv) int32 any)."""
    B, _, K, S = mask.shape
    m = mask[:, 0].astype(jnp.int32).reshape(B, K, nkv, bs).transpose(0, 2, 1, 3)
    tile_any = (m.sum(axis=(2, 3)) > 0).astype(jnp.int32)
    return m, tile_any


def _common_call(
    kernel, grid, in_specs, out_specs, operands, out_shape, scratch, interpret
):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(operands[0]),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands[0], *operands[1])


@functools.partial(jax.jit, static_argnames=("scale", "n_kv", "bs", "interpret"))
def tkg_decode_attention(
    q: jax.Array,  # (B, K, Hq, D)
    k_cache: jax.Array,  # (L, R, S_max, Hkv, D) FULL stacked contiguous cache
    v_cache: jax.Array,
    layer_idx: jax.Array,  # int32 scalar
    mask: jax.Array,  # (B, 1, K, S_kv) bool decode mask, S_kv <= S_max
    sink: jax.Array = None,  # (Hq,) learned sink logits
    *,
    scale: float,
    n_kv: int,
    bs: int = None,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention straight off the stacked contiguous cache (batch row b
    owns cache line b — the sorted-batch convention of read_cache_at_layer).
    Quantized caches (QuantizedKV streams) DMA the int8/fp8 code tiles and
    dequantize in-register (see module docstring). Returns (B, K, Hq, D)."""
    B, K, Hq, D = q.shape
    S_kv = mask.shape[-1]
    if bs is None:
        # default kv tile through the tuning table (KERN704): keyed by the
        # kv bucket and the CACHE dtype (a quantized cache DMAs int8 tiles)
        cache_dt = k_cache.data.dtype if isinstance(k_cache, QuantizedKV) else k_cache.dtype
        bs = tile_default("tkg_decode_attention", f"kv{S_kv}", cache_dt, "bs", 512)
    bs = min(bs, S_kv)
    nkv = S_kv // bs
    n_rep = Hq // n_kv
    rk = n_rep * K
    out_dtype = q.dtype
    quantized = isinstance(k_cache, QuantizedKV)
    if quantized:
        q = _fold_k_dequant(q, k_cache, layer_idx, n_rep)
        k_cache, v_quant = k_cache.data, v_cache
        v_cache = v_cache.data
    qr = _prep_q(q)
    m, tile_any = _mask_tiles(mask, nkv, bs)
    li = jnp.reshape(layer_idx, (1,)).astype(jnp.int32)

    kernel = functools.partial(
        _tkg_kernel, scale=scale, n_kv=n_kv, rk=rk, K=K, nkv=nkv,
        has_sink=sink is not None, n_prefetch=2,
    )
    in_specs = [
        pl.BlockSpec((1, Hq * K, D), lambda b, j, li, ta: (b, 0, 0)),
        pl.BlockSpec((1, 1, K, bs), lambda b, j, li, ta: (b, j, 0, 0)),
    ]
    tensors = [qr, m]
    if sink is not None:
        in_specs.append(pl.BlockSpec((1, Hq), lambda b, j, li, ta: (0, 0)))
        tensors.append(sink.reshape(1, Hq))
    in_specs += [
        pl.BlockSpec((1, 1, bs, n_kv, D), lambda b, j, li, ta: (li[0], b, j, 0, 0)),
        pl.BlockSpec((1, 1, bs, n_kv, D), lambda b, j, li, ta: (li[0], b, j, 0, 0)),
    ]
    tensors += [k_cache, v_cache]

    out = _common_call(
        kernel,
        grid=(B, nkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq * K, D), lambda b, j, li, ta: (b, 0, 0)),
        operands=([li, tile_any], tensors),
        out_shape=jax.ShapeDtypeStruct((B, Hq * K, D), q.dtype),
        scratch=[
            pltpu.VMEM((Hq * K, 1), jnp.float32),
            pltpu.VMEM((Hq * K, 1), jnp.float32),
            pltpu.VMEM((Hq * K, D), jnp.float32),
        ],
        interpret=interpret,
    )
    out = _unprep_out(out, B, K, Hq, D)
    if quantized:
        out = _apply_v_dequant(out, v_quant, layer_idx, n_rep).astype(out_dtype)
    return out


@functools.partial(jax.jit, static_argnames=("scale", "n_kv", "interpret"))
def paged_tkg_decode_attention(
    q: jax.Array,  # (B, K, Hq, D)
    k_cache: jax.Array,  # (L, NB+1, Hkv, bs, D) FULL stacked head-major paged cache
    v_cache: jax.Array,
    layer_idx: jax.Array,  # int32 scalar
    block_table: jax.Array,  # (B, MB) int32
    mask: jax.Array,  # (B, 1, K, MB*bs) bool decode mask over the block view
    sink: jax.Array = None,
    *,
    scale: float,
    n_kv: int,
    interpret: bool = False,
) -> jax.Array:
    """Paged decode attention: cache blocks are DMA'd straight via the block
    table (scalar prefetch) — kills the materializing
    read_block_cache_at_layer gather on the serving decode path
    (reference attention_block_tokengen kernel, attention_base.py:1609).
    Quantized caches DMA the code blocks and dequantize in-register.
    Returns (B, K, Hq, D)."""
    B, K, Hq, D = q.shape
    _, _, Hkv, bs, _ = k_cache.shape
    MB = block_table.shape[1]
    assert mask.shape[-1] == MB * bs, (mask.shape, MB, bs)
    n_rep = Hq // n_kv
    rk = n_rep * K
    out_dtype = q.dtype
    quantized = isinstance(k_cache, QuantizedKV)
    if quantized:
        q = _fold_k_dequant(q, k_cache, layer_idx, n_rep)
        k_cache, v_quant = k_cache.data, v_cache
        v_cache = v_cache.data
    qr = _prep_q(q)
    m, tile_any = _mask_tiles(mask, MB, bs)
    li = jnp.reshape(layer_idx, (1,)).astype(jnp.int32)

    kernel = functools.partial(
        _tkg_kernel, scale=scale, n_kv=n_kv, rk=rk, K=K, nkv=MB,
        has_sink=sink is not None, n_prefetch=3, head_major=True,
    )
    in_specs = [
        pl.BlockSpec((1, Hq * K, D), lambda b, j, li, bt, ta: (b, 0, 0)),
        pl.BlockSpec((1, 1, K, bs), lambda b, j, li, bt, ta: (b, j, 0, 0)),
    ]
    tensors = [qr, m]
    if sink is not None:
        in_specs.append(pl.BlockSpec((1, Hq), lambda b, j, li, bt, ta: (0, 0)))
        tensors.append(sink.reshape(1, Hq))
    in_specs += [
        pl.BlockSpec(
            (1, 1, n_kv, bs, D), lambda b, j, li, bt, ta: (li[0], bt[b, j], 0, 0, 0)
        ),
        pl.BlockSpec(
            (1, 1, n_kv, bs, D), lambda b, j, li, bt, ta: (li[0], bt[b, j], 0, 0, 0)
        ),
    ]
    tensors += [k_cache, v_cache]

    out = _common_call(
        kernel,
        grid=(B, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq * K, D), lambda b, j, li, bt, ta: (b, 0, 0)),
        operands=([li, block_table.astype(jnp.int32), tile_any], tensors),
        out_shape=jax.ShapeDtypeStruct((B, Hq * K, D), q.dtype),
        scratch=[
            pltpu.VMEM((Hq * K, 1), jnp.float32),
            pltpu.VMEM((Hq * K, 1), jnp.float32),
            pltpu.VMEM((Hq * K, D), jnp.float32),
        ],
        interpret=interpret,
    )
    out = _unprep_out(out, B, K, Hq, D)
    if quantized:
        out = _apply_v_dequant(out, v_quant, layer_idx, n_rep).astype(out_dtype)
    return out
