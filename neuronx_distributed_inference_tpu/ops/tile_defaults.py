"""Tile defaults sourced from the committed tuning table (KERN704).

Every Pallas kernel in ``ops/`` resolves its default tile sizes through
:func:`tile_default` instead of a hard-coded constant. The values live in
``analysis/tuning_table.json``, keyed by (kernel, shape-class, dtype), with
a ``provenance`` field: ``hand_picked`` entries mirror the historical
in-code constants (the kernel audit errors if they drift apart — see
KERN704 in ``analysis/kernel_audit.py``); a hardware session that re-runs
the ``scripts/prefill_profile.py`` / ``scripts/decode_scaling.py`` sweeps
promotes them to ``measured``, at which point the table — not this file's
fallbacks — is the source of truth.

This module must stay import-light (json + pathlib only): the kernels pull
defaults at trace time and must not drag the analysis package, jax-extras,
or anything traced into their import graph. A missing or unreadable table
falls back to the caller-supplied constant so ``ops/`` keeps working from a
bare checkout; the kernel-audit gate is what enforces the table exists and
agrees.
"""

import json
import pathlib
from contextlib import contextmanager
from functools import lru_cache
from typing import Dict, Iterator, Optional

#: the committed table, next to the suite that audits it
TUNING_TABLE_PATH = (
    pathlib.Path(__file__).resolve().parent.parent
    / "analysis"
    / "tuning_table.json"
)

#: accepted provenance values, in promotion order
PROVENANCES = ("hand_picked", "measured")


@lru_cache(maxsize=1)
def _load_table() -> Dict:
    try:
        with open(TUNING_TABLE_PATH) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def reload_table() -> None:
    """Drop the cached table (tests and ``--write-baseline`` use this)."""
    _load_table.cache_clear()


def table_entry(kernel: str, shape_class: str, dtype: str) -> Optional[Dict]:
    """The raw table entry ``{"tiles": {...}, "provenance": ...}`` or None."""
    entry = (
        _load_table()
        .get("kernels", {})
        .get(kernel, {})
        .get(shape_class, {})
        .get(str(dtype))
    )
    return entry if isinstance(entry, dict) else None


#: candidate-injection stack for the kernel audit's ``legal_tiles``
#: enumeration: overrides win over both the table and the fallback, so a
#: candidate exercises exactly the lookup path a committed table entry
#: would. Single-threaded by design (the analysis gate and tests).
_OVERRIDES: list = []


@contextmanager
def tile_overrides(kernel: str, tiles: Dict[str, int]) -> Iterator[None]:
    """Force ``tile_default(kernel, ...)`` to return ``tiles[param]`` for
    the duration of the context, regardless of table/fallback. NOTE: jitted
    kernel wrappers cache traces on shapes/statics only — callers must
    trace the unjitted function (see ``analysis.kernel_registry._unjit``)
    or clear jit caches around the context."""
    _OVERRIDES.append((kernel, dict(tiles)))
    try:
        yield
    finally:
        _OVERRIDES.pop()


def tile_default(
    kernel: str, shape_class: str, dtype: str, param: str, fallback: int
) -> int:
    """Default for one tile parameter of ``kernel`` at (shape_class, dtype).

    ``fallback`` is the historical hand-picked constant; it is used when the
    table has no entry (bare checkout, or a kernel/shape the table does not
    cover yet). While the entry's provenance is ``hand_picked`` the audit
    pins table == fallback, so the two can only diverge through a reviewed
    table regeneration.
    """
    for over_kernel, over_tiles in reversed(_OVERRIDES):
        if over_kernel == kernel and param in over_tiles:
            return int(over_tiles[param])
    entry = table_entry(kernel, shape_class, dtype)
    if entry is None:
        return fallback
    tiles = entry.get("tiles", {})
    value = tiles.get(param, fallback)
    return int(value) if isinstance(value, (int, float)) else fallback
