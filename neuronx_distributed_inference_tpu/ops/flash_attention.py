"""Pallas flash attention (causal/windowed/chunked prefill) for TPU.

TPU-native replacement for the reference's NKI flash-attention kernels
(reference: neuronxcc ``attention_isa_kernel`` used at
modules/attention/attention_base.py:54,720; in-tree cores
modules/chunked_prefill/flash_attn_core.py:70 and the sliding-window
``flash_fwd`` modules/sliding_window/attention.py:61-233).

Design: classic online-softmax flash attention tiled for the MXU.
Grid = (batch, heads, q_blocks, kv_blocks); the kv_blocks axis is the
innermost sequential loop; running max/denominator/accumulator live in VMEM
scratch across kv steps. Tiles entirely outside the mask are skipped:
above the causal diagonal, fully below the sliding window, or in a
non-overlapping attention chunk (the reference sliding-window kernel's
fully-masked-tile skip, sliding_window/attention.py:61-233).

Learned attention sinks are folded in OUTSIDE the kernel: the kernel emits
per-row (m, l) softmax stats and the wrapper rescales the output by
``l / (l + exp(sink - m))`` — exactly the sink-in-denominator semantics
(reference attention_base.py:879-889) with no extra kernel passes.

Falls back to an XLA masked-softmax path off-TPU or for shapes the kernel
doesn't support (the reference similarly keeps a native softmax path,
attention_base.py:720-891).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.kernel_mode import kernel_interpret

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _flash_kernel(
    q_ref,  # (1, 1, bq, D)
    k_ref,  # (1, 1, bkv, D)
    v_ref,  # (1, 1, bkv, D)
    valid_ref,  # (1, 1, bkv) int32 key-validity
    o_ref,  # (1, 1, bq, D)
    m_ref,  # (1, 1, bq, 1) f32 row max (for sink folding)
    l_ref,  # (1, 1, bq, 1) f32 row denom
    m_scr,  # (bq, 1) f32 running max
    l_scr,  # (bq, 1) f32 running denom
    acc_scr,  # (bq, D) f32 accumulator
    *,
    scale: float,
    bq: int,
    bkv: int,
    nkv: int,
    causal: bool,
    window: Optional[int],
    chunk: Optional[int],
):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = iq * bq
    kv_start = ik * bkv
    q_last = q_start + bq - 1

    # skip tiles entirely outside the mask: above the causal diagonal,
    # fully below the sliding window, or in a non-overlapping chunk
    run = jnp.bool_(True) if not causal else (kv_start <= q_last)
    if window is not None:
        # rows attend (row - window, row]: a tile is dead when its LAST kv
        # column is <= the FIRST row - window
        run = jnp.logical_and(run, kv_start + bkv - 1 > q_start - window)
    if chunk is not None:
        # same-chunk attention only: tile chunk ranges must overlap
        run = jnp.logical_and(run, (kv_start // chunk) <= (q_last // chunk))
        run = jnp.logical_and(run, ((kv_start + bkv - 1) // chunk) >= (q_start // chunk))

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # (bq, bkv)

        valid = valid_ref[0, 0, :] > 0  # (bkv,)
        mask = jnp.broadcast_to(valid[None, :], (bq, bkv))
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
        cols = kv_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
        if causal:
            mask = mask & (cols <= rows)
        if window is not None:
            mask = mask & (cols > rows - window)
        if chunk is not None:
            mask = mask & ((cols // chunk) == (rows // chunk))
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]  # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # (bq, bkv)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)  # (bq, 1)

        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(ik == nkv - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0, :, :] = (acc_scr[:] / denom).astype(o_ref.dtype)
        m_ref[0, 0, :, :] = m_scr[:]
        l_ref[0, 0, :, :] = l_scr[:]


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "window", "chunk", "bq", "bkv", "interpret"),
)
def flash_attention_bhsd(
    q: jax.Array,  # (B, H, S, D)
    k: jax.Array,  # (B, H, S, D)
    v: jax.Array,
    key_valid: jax.Array,  # (B, S) int32
    *,
    scale: float,
    causal: bool = True,
    window: Optional[int] = None,
    chunk: Optional[int] = None,
    bq: Optional[int] = None,
    bkv: Optional[int] = None,
    interpret: bool = False,
):
    """Returns (out (B,H,S,D), m (B,H,S,1), l (B,H,S,1)).

    Default tiles are picked by a measured rule (v5e tile sweep, PERF.md
    "Prefill efficiency" round-5 section): plain-causal attention runs 3.1x
    faster at 512x512 than the old 128x128 default at S=8192 (fewer grid
    steps => less per-step pipeline overhead; VMEM comfortably fits the f32
    accumulator at D<=128). Windowed/chunked flavors keep 128x128: live
    kernel work scales as S*(window + bq), so a 512-row q tile would do up
    to (window+512)/(window+128) more masked-flavor work than the skip
    granularity saves."""
    B, H, S, D = q.shape
    masked = window is not None or chunk is not None
    if bq is None:
        bq = 128 if masked else 512
    if bkv is None:
        bkv = 128 if masked else 512
    bq = min(bq, S)
    bkv = min(bkv, S)
    nq = pl.cdiv(S, bq)
    nkv = pl.cdiv(S, bkv)

    kernel = functools.partial(
        _flash_kernel, scale=scale, bq=bq, bkv=bkv, nkv=nkv, causal=causal,
        window=window, chunk=chunk,
    )
    grid = (B, H, nq, nkv)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            # (B, 1, S) with a unit middle axis: Mosaic requires the block's
            # last-two dims divisible by (8, 128) OR equal to the array dims —
            # block (1, bkv) over a (B, S) array fails for B > 1, so the
            # validity mask carries a dummy axis making the block (1, bkv)
            # sit over array dims (1, S).
            pl.BlockSpec((1, 1, bkv), lambda b, h, iq, ik: (b, 0, ik)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, key_valid[:, None, :])


def flash_attention(
    q, k, v, key_valid, spec, causal: bool = True,
    window: Optional[int] = None, chunk: Optional[int] = None, sink=None,
):
    """Flash attention entry. q/k/v: (B, S, H, D) with H already GQA-repeated;
    key_valid: (B, S). ``window``/``chunk`` select the sliding-window /
    chunked-attention prefill masks; ``sink`` (Hq,) folds learned sink logits
    into the softmax denominator via the emitted (m, l) stats. Returns
    (B, S, H, D)."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, m, l = flash_attention_bhsd(
        qt,
        kt,
        vt,
        key_valid.astype(jnp.int32),
        scale=spec.softmax_scale,
        causal=causal,
        window=window,
        chunk=chunk,
        interpret=kernel_interpret(),
    )
    if sink is not None:
        # softmax-with-sink = softmax * l / (l + exp(sink - m))
        # (reference sink-in-denominator, attention_base.py:879-889)
        sk = sink.astype(jnp.float32)[None, :, None, None]  # (1, H, 1, 1)
        factor = l / (l + jnp.exp(sk - m))
        out = (out.astype(jnp.float32) * factor).astype(out.dtype)
    return jnp.swapaxes(out, 1, 2)
