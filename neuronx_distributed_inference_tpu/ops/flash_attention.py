"""Pallas flash attention (causal prefill) for TPU.

TPU-native replacement for the reference's NKI flash-attention kernels
(reference: neuronxcc ``attention_isa_kernel`` used at
modules/attention/attention_base.py:54,720; in-tree core
modules/chunked_prefill/flash_attn_core.py:70).

Design: classic online-softmax flash attention tiled for the MXU.
Grid = (batch, heads, q_blocks, kv_blocks); the kv_blocks axis is the
innermost sequential loop; running max/denominator/accumulator live in VMEM
scratch across kv steps. Causal tiles entirely above the diagonal are skipped
(reference's tile scheduler skips fully-masked tiles,
modules/sliding_window/attention.py:61-233).

Falls back to an XLA masked-softmax path off-TPU or for shapes the kernel
doesn't support (the reference similarly keeps a native softmax path,
attention_base.py:720-891).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _flash_kernel(
    q_ref,  # (1, 1, bq, D)
    k_ref,  # (1, 1, bkv, D)
    v_ref,  # (1, 1, bkv, D)
    valid_ref,  # (1, bkv) int32 key-validity
    o_ref,  # (1, 1, bq, D)
    m_scr,  # (bq, 1) f32 running max
    l_scr,  # (bq, 1) f32 running denom
    acc_scr,  # (bq, D) f32 accumulator
    *,
    scale: float,
    bq: int,
    bkv: int,
    nkv: int,
    causal: bool,
):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = iq * bq
    kv_start = ik * bkv

    # skip tiles entirely above the causal diagonal
    run = (not causal) or (kv_start <= q_start + bq - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # (bq, bkv)

        valid = valid_ref[0, :] > 0  # (bkv,)
        mask = jnp.broadcast_to(valid[None, :], (bq, bkv))
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            cols = kv_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            mask = mask & (cols <= rows)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]  # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # (bq, bkv)
        alpha = jnp.exp(m_prev - m_new)  # (bq, 1)

        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(ik == nkv - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0, :, :] = (acc_scr[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "bq", "bkv", "interpret"))
def flash_attention_bhsd(
    q: jax.Array,  # (B, H, S, D)
    k: jax.Array,  # (B, H, S, D)
    v: jax.Array,
    key_valid: jax.Array,  # (B, S) int32
    *,
    scale: float,
    causal: bool = True,
    bq: int = 128,
    bkv: int = 128,
    interpret: bool = False,
) -> jax.Array:
    B, H, S, D = q.shape
    bq = min(bq, S)
    bkv = min(bkv, S)
    nq = pl.cdiv(S, bq)
    nkv = pl.cdiv(S, bkv)

    kernel = functools.partial(
        _flash_kernel, scale=scale, bq=bq, bkv=bkv, nkv=nkv, causal=causal
    )
    grid = (B, H, nq, nkv)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, bkv), lambda b, h, iq, ik: (b, ik)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, key_valid)


def flash_attention(q, k, v, key_valid, spec, causal: bool = True):
    """Flash attention entry. q/k/v: (B, S, H, D) with H already GQA-repeated;
    key_valid: (B, S). Returns (B, S, H, D)."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bhsd(
        qt,
        kt,
        vt,
        key_valid.astype(jnp.int32),
        scale=spec.softmax_scale,
        causal=causal,
        interpret=jax.default_backend() != "tpu",
    )
    return jnp.swapaxes(out, 1, 2)
