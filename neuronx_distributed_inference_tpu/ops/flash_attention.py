"""Pallas flash attention (causal/windowed/chunked prefill) for TPU.

TPU-native replacement for the reference's NKI flash-attention kernels
(reference: neuronxcc ``attention_isa_kernel`` used at
modules/attention/attention_base.py:54,720; in-tree cores
modules/chunked_prefill/flash_attn_core.py:70 and the sliding-window
``flash_fwd`` modules/sliding_window/attention.py:61-233).

Design: classic online-softmax flash attention tiled for the MXU.
Grid = (batch, heads, q_blocks, kv_blocks); the kv_blocks axis is the
innermost sequential loop; running max/denominator/accumulator live in VMEM
scratch across kv steps. Tiles entirely outside the mask are skipped:
above the causal diagonal, fully below the sliding window, or in a
non-overlapping attention chunk (the reference sliding-window kernel's
fully-masked-tile skip, sliding_window/attention.py:61-233).

Learned attention sinks are folded in OUTSIDE the kernel: the kernel emits
per-row (m, l) softmax stats and the wrapper rescales the output by
``l / (l + exp(sink - m))`` — exactly the sink-in-denominator semantics
(reference attention_base.py:879-889) with no extra kernel passes.

Head-packed variant (``packed=True``, head_dim 64): pairs of heads are laid
out side by side in one 128-lane tile — (B, H, S, 64) -> (B, H/2, S, 128) —
so the Q·Kᵀ contraction runs at the MXU's full 128 depth instead of
half-filling it. Cross-head partial products are suppressed by a
BLOCK-DIAGONAL K/V layout (two independent 64-deep accumulations side by
side in the 128-wide tile): K is stacked [[K₀|0], [0|K₁]] (2·bkv, 128), so
Q_packed @ K_bdᵀ yields (bq, 2·bkv) = [S₀ | S₁] with zero cross terms.
Online-softmax stats (m, l) stay per-head inside the tile; the softmax
exp/rescale intermediates run in bf16 (VPU bf16 is 2x fp32 on v5e) while
m/l/accumulator stay fp32. The PV product uses the same block-diagonal V:
P (bq, 2·bkv) @ V_bd (2·bkv, 128) = [P₀V₀ | P₁V₁] — full contraction depth
AND full 128-lane output width. Odd head counts pad with a duplicate of the
last head (one wasted head-pair slot) and slice after. PERF.md round 6 has
the arithmetic; the packing halves grid steps, fully packs every 128-lane
register the VPU touches, and moves the PV matmul off the fp32 MXU path.

Falls back to an XLA masked-softmax path off-TPU or for shapes the kernel
doesn't support (the reference similarly keeps a native softmax path,
attention_base.py:720-891).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.kernel_mode import kernel_interpret
from neuronx_distributed_inference_tpu.ops.tile_defaults import tile_default

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _tile_live(q_start, q_last, kv_start, bkv, *, causal, window, chunk):
    """Dead-tile skip predicate shared by the packed and unpacked kernels:
    True unless the (bq, bkv) tile lies entirely outside the mask — above
    the causal diagonal, fully below the sliding window, or in a
    non-overlapping attention chunk (the reference sliding-window kernel's
    fully-masked-tile skip, sliding_window/attention.py:61-233)."""
    run = jnp.bool_(True) if not causal else (kv_start <= q_last)
    if window is not None:
        # rows attend (row - window, row]: a tile is dead when its LAST kv
        # column is <= the FIRST row - window
        run = jnp.logical_and(run, kv_start + bkv - 1 > q_start - window)
    if chunk is not None:
        # same-chunk attention only: tile chunk ranges must overlap
        run = jnp.logical_and(run, (kv_start // chunk) <= (q_last // chunk))
        run = jnp.logical_and(run, ((kv_start + bkv - 1) // chunk) >= (q_start // chunk))
    return run


def _tile_mask(valid, q_start, kv_start, bq, bkv, *, causal, window, chunk):
    """(bq, bkv) boolean mask for one tile — key validity fused with the
    causal/window/chunk flavors. Shared by both kernels so the semantics
    cannot drift between them."""
    mask = jnp.broadcast_to(valid[None, :], (bq, bkv))
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
    cols = kv_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
    if causal:
        mask = mask & (cols <= rows)
    if window is not None:
        mask = mask & (cols > rows - window)
    if chunk is not None:
        mask = mask & ((cols // chunk) == (rows // chunk))
    return mask


def _flash_kernel(
    q_ref,  # (1, 1, bq, D)
    k_ref,  # (1, 1, bkv, D)
    v_ref,  # (1, 1, bkv, D)
    valid_ref,  # (1, 1, bkv) int32 key-validity
    o_ref,  # (1, 1, bq, D)
    m_ref,  # (1, 1, bq, 1) f32 row max (for sink folding)
    l_ref,  # (1, 1, bq, 1) f32 row denom
    m_scr,  # (bq, 1) f32 running max
    l_scr,  # (bq, 1) f32 running denom
    acc_scr,  # (bq, D) f32 accumulator
    *,
    scale: float,
    bq: int,
    bkv: int,
    nkv: int,
    causal: bool,
    window: Optional[int],
    chunk: Optional[int],
):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = iq * bq
    kv_start = ik * bkv
    q_last = q_start + bq - 1
    run = _tile_live(
        q_start, q_last, kv_start, bkv, causal=causal, window=window, chunk=chunk
    )

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # (bq, bkv)

        valid = valid_ref[0, 0, :] > 0  # (bkv,)
        mask = _tile_mask(
            valid, q_start, kv_start, bq, bkv, causal=causal, window=window,
            chunk=chunk,
        )
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]  # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # (bq, bkv)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)  # (bq, 1)

        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(ik == nkv - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0, 0, :, :] = (acc_scr[:] / denom).astype(o_ref.dtype)
        m_ref[0, 0, :, :] = m_scr[:]
        l_ref[0, 0, :, :] = l_scr[:]


def _flash_kernel_packed(
    q_ref,  # (1, 1, bq, 2d) — [head 2p | head 2p+1] side by side
    k_ref,  # (1, 1, bkv, 2d)
    v_ref,  # (1, 1, bkv, 2d)
    valid_ref,  # (1, 1, bkv) int32 key-validity
    o_ref,  # (1, 1, bq, 2d)
    m_ref,  # (1, 1, 2, bq, 1) f32 per-head row max
    l_ref,  # (1, 1, 2, bq, 1) f32 per-head row denom
    m0_scr,  # (bq, 1) f32 running max, even head
    m1_scr,  # (bq, 1) f32 running max, odd head
    l0_scr,  # (bq, 1) f32 running denom, even head
    l1_scr,  # (bq, 1) f32 running denom, odd head
    acc_scr,  # (bq, 2d) f32 packed accumulator
    *,
    scale: float,
    bq: int,
    bkv: int,
    nkv: int,
    causal: bool,
    window: Optional[int],
    chunk: Optional[int],
    d: int,
    softmax_bf16: bool,
):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m0_scr[:] = jnp.full_like(m0_scr, NEG_INF)
        m1_scr[:] = jnp.full_like(m1_scr, NEG_INF)
        l0_scr[:] = jnp.zeros_like(l0_scr)
        l1_scr[:] = jnp.zeros_like(l1_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = iq * bq
    kv_start = ik * bkv
    q_last = q_start + bq - 1
    # both packed heads see the same positions, so the shared skip
    # predicate and mask builder apply unchanged
    run = _tile_live(
        q_start, q_last, kv_start, bkv, causal=causal, window=window, chunk=chunk
    )

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]  # (bq, 2d)
        k = k_ref[0, 0]  # (bkv, 2d)
        if not softmax_bf16:
            # parity mode: fp32 operands reproduce the unpacked kernel's
            # numerics (bf16 MXU inputs accumulate identically in f32, but
            # the exp/PV below also stay f32)
            q = q.astype(jnp.float32)
            k = k.astype(jnp.float32)
        # block-diagonal K (2bkv, 2d): row r keeps lanes of ITS head only —
        # (r >= bkv) == (lane >= d). Zeros kill the cross-head partials, so
        # one full-128-deep contraction emits both heads' score tiles.
        k2 = jnp.concatenate([k, k], axis=0)
        rhalf = jax.lax.broadcasted_iota(jnp.int32, (2 * bkv, 2 * d), 0) >= bkv
        chalf = jax.lax.broadcasted_iota(jnp.int32, (2 * bkv, 2 * d), 1) >= d
        bd = rhalf == chalf
        k_bd = jnp.where(bd, k2, jnp.zeros_like(k2))
        s = jax.lax.dot_general(
            q, k_bd, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # (bq, 2bkv) = [S_even | S_odd]

        valid = valid_ref[0, 0, :] > 0  # (bkv,)
        mask = _tile_mask(
            valid, q_start, kv_start, bq, bkv, causal=causal, window=window,
            chunk=chunk,
        )
        mask2 = jnp.concatenate([mask, mask], axis=1)  # (bq, 2bkv)
        s = jnp.where(mask2, s, NEG_INF)

        s0 = s[:, :bkv]
        s1 = s[:, bkv:]
        m0 = jnp.maximum(m0_scr[:], jnp.max(s0, axis=1, keepdims=True))
        m1 = jnp.maximum(m1_scr[:], jnp.max(s1, axis=1, keepdims=True))
        # exp in bf16 (stats stay f32): the O(bq*bkv) VPU exp is the
        # softmax floor at D=64 and bf16 doubles VPU throughput on v5e
        pdt = jnp.bfloat16 if softmax_bf16 else jnp.float32
        t = jnp.concatenate([s0 - m0, s1 - m1], axis=1)  # f32, <= 0
        p = jnp.exp(t.astype(pdt))  # (bq, 2bkv)
        p = jnp.where(mask2, p, jnp.zeros_like(p))
        a0 = jnp.exp(m0_scr[:] - m0)  # (bq, 1) f32
        a1 = jnp.exp(m1_scr[:] - m1)
        l0_scr[:] = l0_scr[:] * a0 + jnp.sum(
            p[:, :bkv].astype(jnp.float32), axis=1, keepdims=True
        )
        l1_scr[:] = l1_scr[:] * a1 + jnp.sum(
            p[:, bkv:].astype(jnp.float32), axis=1, keepdims=True
        )

        v = v_ref[0, 0]
        if not softmax_bf16:
            v = v.astype(jnp.float32)
        v2 = jnp.concatenate([v, v], axis=0)
        v_bd = jnp.where(bd, v2, jnp.zeros_like(v2))
        pv = jax.lax.dot_general(
            p, v_bd.astype(p.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, 2d) = [P0@V0 | P1@V1]
        lane = jax.lax.broadcasted_iota(jnp.int32, (bq, 2 * d), 1)
        alpha = jnp.where(
            lane < d,
            jnp.broadcast_to(a0, (bq, 2 * d)),
            jnp.broadcast_to(a1, (bq, 2 * d)),
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m0_scr[:] = m0
        m1_scr[:] = m1

    @pl.when(ik == nkv - 1)
    def _finalize():
        d0 = jnp.maximum(l0_scr[:], 1e-30)
        d1 = jnp.maximum(l1_scr[:], 1e-30)
        lane = jax.lax.broadcasted_iota(jnp.int32, (bq, 2 * d), 1)
        denom = jnp.where(
            lane < d,
            jnp.broadcast_to(d0, (bq, 2 * d)),
            jnp.broadcast_to(d1, (bq, 2 * d)),
        )
        o_ref[0, 0, :, :] = (acc_scr[:] / denom).astype(o_ref.dtype)
        m_ref[0, 0, 0, :, :] = m0_scr[:]
        m_ref[0, 0, 1, :, :] = m1_scr[:]
        l_ref[0, 0, 0, :, :] = l0_scr[:]
        l_ref[0, 0, 1, :, :] = l1_scr[:]


def _packed_flash_call(
    q, k, v, key_valid, *, scale, causal, window, chunk, bq, bkv, interpret,
    softmax_bf16,
):
    """Head-pair packed kernel launch: (B, H, S, 64) -> (B, ceil(H/2), S, 128)
    pairs, block-diagonal contraction, per-head (m, l). Returns the UNPACKED
    (out, m, l) triple with the same shapes as the plain kernel."""
    B, H, S, D = q.shape
    if D > 64:
        raise ValueError(f"head packing needs head_dim <= 64, got {D}")
    Hp = H + (H % 2)
    if Hp != H:
        # odd head count: pad with a duplicate of the last head (one wasted
        # 64-lane half in the final pair) and slice it off after
        q, k, v = (jnp.concatenate([x, x[:, -1:]], axis=1) for x in (q, k, v))
    P = Hp // 2

    def pack(x):
        return (
            x.reshape(B, P, 2, S, D)
            .transpose(0, 1, 3, 2, 4)
            .reshape(B, P, S, 2 * D)
        )

    nq = pl.cdiv(S, bq)
    nkv = pl.cdiv(S, bkv)
    kernel = functools.partial(
        _flash_kernel_packed, scale=scale, bq=bq, bkv=bkv, nkv=nkv,
        causal=causal, window=window, chunk=chunk, d=D,
        softmax_bf16=softmax_bf16,
    )
    grid = (B, P, nq, nkv)
    out, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, 2 * D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bkv, 2 * D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bkv, 2 * D), lambda b, h, iq, ik: (b, h, ik, 0)),
            # dummy middle axis — same Mosaic block-divisibility workaround
            # as the unpacked kernel (see flash_attention_bhsd)
            pl.BlockSpec((1, 1, bkv), lambda b, h, iq, ik: (b, 0, ik)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, 2 * D), lambda b, h, iq, ik: (b, h, iq, 0)),
            # per-head stats ride a SIZE-2 head-half axis (not 2 lanes): the
            # (bq, 1) trailing block keeps the layout the unpacked kernel
            # already lowers
            pl.BlockSpec((1, 1, 2, bq, 1), lambda b, h, iq, ik: (b, h, 0, iq, 0)),
            pl.BlockSpec((1, 1, 2, bq, 1), lambda b, h, iq, ik: (b, h, 0, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, P, S, 2 * D), q.dtype),
            jax.ShapeDtypeStruct((B, P, 2, S, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, P, 2, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 2 * D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(pack(q), pack(k), pack(v), key_valid[:, None, :])

    out = (
        out.reshape(B, P, S, 2, D)
        .transpose(0, 1, 3, 2, 4)
        .reshape(B, Hp, S, D)
    )
    m = m.reshape(B, Hp, S, 1)  # (B, P, 2, S, 1): (pair, half) == head order
    l = l.reshape(B, Hp, S, 1)
    if Hp != H:
        out, m, l = out[:, :H], m[:, :H], l[:, :H]
    return out, m, l


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "window", "chunk", "bq", "bkv", "interpret",
        "packed", "softmax_bf16",
    ),
)
def flash_attention_bhsd(
    q: jax.Array,  # (B, H, S, D)
    k: jax.Array,  # (B, H, S, D)
    v: jax.Array,
    key_valid: jax.Array,  # (B, S) int32
    *,
    scale: float,
    causal: bool = True,
    window: Optional[int] = None,
    chunk: Optional[int] = None,
    bq: Optional[int] = None,
    bkv: Optional[int] = None,
    interpret: bool = False,
    packed: bool = False,
    softmax_bf16: Optional[bool] = None,
):
    """Returns (out (B,H,S,D), m (B,H,S,1), l (B,H,S,1)).

    Default tiles are picked by a measured rule (v5e tile sweep, PERF.md
    "Prefill efficiency" round-5 section): plain-causal attention runs 3.1x
    faster at 512x512 than the old 128x128 default at S=8192 (fewer grid
    steps => less per-step pipeline overhead; VMEM comfortably fits the f32
    accumulator at D<=128). Windowed/chunked flavors keep 128x128: live
    kernel work scales as S*(window + bq), so a 512-row q tile would do up
    to (window+512)/(window+128) more masked-flavor work than the skip
    granularity saves. The packed path keeps both rules — packing halves
    the head-grid axis and doubles per-tile lanes without changing the
    (bq, bkv) trade-off (PERF.md round 6).

    ``packed``: head-pair packing for head_dim <= 64 (module docstring) —
    all mask flavors supported. ``softmax_bf16`` (packed path only): run the
    softmax exp/PV intermediates in bf16 with fp32 stats/accumulators;
    default (None) = bf16 exactly when the inputs are bf16 — a KERNEL-LEVEL
    default for direct callers (tile sweeps). The model path
    (:func:`flash_attention`) always passes the ``attention_softmax_fp32``
    config decision explicitly instead."""
    B, H, S, D = q.shape
    masked = window is not None or chunk is not None
    # defaults read through the committed tuning table (KERN704); the
    # literals passed as fallbacks are the historical hand-picked rule and
    # the audit pins table == fallback until a hardware sweep promotes the
    # entry to provenance "measured"
    shape_class = "masked" if masked else "plain"
    if bq is None:
        bq = tile_default(
            "flash_attention", shape_class, q.dtype, "bq", 128 if masked else 512
        )
    if bkv is None:
        bkv = tile_default(
            "flash_attention", shape_class, q.dtype, "bkv", 128 if masked else 512
        )
    bq = min(bq, S)
    bkv = min(bkv, S)
    if packed:
        if softmax_bf16 is None:
            softmax_bf16 = q.dtype == jnp.bfloat16
        return _packed_flash_call(
            q, k, v, key_valid.astype(jnp.int32), scale=scale, causal=causal,
            window=window, chunk=chunk, bq=bq, bkv=bkv, interpret=interpret,
            softmax_bf16=softmax_bf16,
        )
    nq = pl.cdiv(S, bq)
    nkv = pl.cdiv(S, bkv)

    kernel = functools.partial(
        _flash_kernel, scale=scale, bq=bq, bkv=bkv, nkv=nkv, causal=causal,
        window=window, chunk=chunk,
    )
    grid = (B, H, nq, nkv)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            # (B, 1, S) with a unit middle axis: Mosaic requires the block's
            # last-two dims divisible by (8, 128) OR equal to the array dims —
            # block (1, bkv) over a (B, S) array fails for B > 1, so the
            # validity mask carries a dummy axis making the block (1, bkv)
            # sit over array dims (1, S).
            pl.BlockSpec((1, 1, bkv), lambda b, h, iq, ik: (b, 0, ik)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, key_valid[:, None, :])


def flash_attention(
    q, k, v, key_valid, spec, causal: bool = True,
    window: Optional[int] = None, chunk: Optional[int] = None, sink=None,
    packed: bool = False,
):
    """Flash attention entry. q/k/v: (B, S, H, D) with H already GQA-repeated;
    key_valid: (B, S). ``window``/``chunk`` select the sliding-window /
    chunked-attention prefill masks; ``sink`` (Hq,) folds learned sink logits
    into the softmax denominator via the emitted (m, l) stats; ``packed``
    selects the head-pair packed kernel (decided by the dispatch layer,
    modules/attention._use_packed). The packed kernel's bf16 softmax
    intermediates honor ``spec.softmax_fp32`` (config
    attention_softmax_fp32, default True -> fp32 exp/PV exactly like the
    unpacked kernel; set it False to opt into the bf16 VPU/MXU win).
    Returns (B, S, H, D)."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, m, l = flash_attention_bhsd(
        qt,
        kt,
        vt,
        key_valid.astype(jnp.int32),
        scale=spec.softmax_scale,
        causal=causal,
        window=window,
        chunk=chunk,
        interpret=kernel_interpret(),
        packed=packed,
        softmax_bf16=not spec.softmax_fp32 if packed else None,
    )
    if sink is not None:
        # softmax-with-sink = softmax * l / (l + exp(sink - m))
        # (reference sink-in-denominator, attention_base.py:879-889)
        sk = sink.astype(jnp.float32)[None, :, None, None]  # (1, H, 1, 1)
        factor = l / (l + jnp.exp(sk - m))
        out = (out.astype(jnp.float32) * factor).astype(out.dtype)
    return jnp.swapaxes(out, 1, 2)
