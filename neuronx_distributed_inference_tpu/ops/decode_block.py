"""Fused decode-layer Pallas kernels: the attention BLOCK and the MLP BLOCK.

TPU-native re-design of the reference's token-generation "mega" kernel
(reference: modules/attention/attention_base.py:1609
``attention_block_tokengen_nki_kernel`` — rmsnorm + fused-QKV + RoPE +
attention + output projection in one kernel, with the K/V returned for an
outside cache update when ``update_cache_in_kernel`` is off; plus the fused
MLP kernels the reference pairs with it).

Why: the bf16 decode step is HBM-bound; profiling (PERF.md) shows ~37 us/layer
of overhead over the bandwidth ideal, split between small-op dispatch around
the attention block (cache bucket read, norm/rope/scatter glue) and the
gate/up/down MLP running as two XLA fusions. These kernels stream every weight
tile exactly once through a single software pipeline per block, so the layer
approaches the pure weight-DMA roofline.

Design — one flat grid per batch row, phase-switched by step index:

  ``fused_attn_block``: grid (B, nA + nkv + nC)
    phase A (nA steps): rms-normed x @ W_qkv tile -> qkv accumulator (VMEM)
    step nA: per-head RoPE + rep-major row relayout; ACTIVE (in-flight)
      attention among the K new tokens; emits k_new/v_new for the cache
      scatter OUTSIDE the kernel (reference update_cache_in_kernel=False)
    phase B (nkv steps): online-softmax attention over PRIOR cache tiles
      DMA'd straight from the full stacked cache (layer + row via scalar
      prefetch); fully-masked tiles skipped
    phase C (nC steps): finalized attention rows @ W_out tile + residual ->
      output hidden tile

  ``fused_mlp_block``: grid (B, nI)
    each step streams one (H, TI) gate tile, one (H, TI) up tile and one
    (TI, H) down tile: acc += act(norm(x) @ Wg_t) * (norm(x) @ Wu_t) @ Wd_t;
    the last step writes x + acc.

The prior-cache mask must EXCLUDE the slots being written this step (the
cache scatter happens after the kernel; in-flight tokens are handled by the
ACTIVE part) — the wrapper prunes columns [pos, pos+K) from the decode mask,
the exact prior/active decomposition of the reference kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.decode_attention import _mask_tiles
from neuronx_distributed_inference_tpu.ops.tile_defaults import tile_default

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def use_fused_attn_block(spec, q_len: int, kv_width: int) -> bool:
    """Gate for the fused attention-block kernel (``spec`` is an AttnSpec).
    Config flag semantics match the other kernels: None = auto on TPU,
    True = force (still honoring shape guards), False = off."""
    enabled = spec.use_fused_block
    if enabled is False:
        return False
    ok = (
        q_len <= 16
        and spec.head_dim % 64 == 0
        and not spec.qkv_bias
        and not spec.o_bias
        and not spec.qk_norm
        and spec.qkv_clip is None
        and not spec.has_sink
        and kv_width >= 128
        and kv_width % min(512, kv_width) == 0
    )
    if enabled:
        return ok
    # AUTO = OFF: measured on a v5e (PERF.md round 4), the fused block loses
    # ~5% to the XLA-fused native path at bs=1 — per-grid-step pipeline
    # overhead outweighs the DMA savings when XLA is already at 80-92% of
    # the bandwidth roofline. The kernel stays available (force True) and
    # fully parity/lowering-tested; revisit on hardware where XLA fuses
    # worse or at batch sizes where the step count amortizes.
    return False


def _rms(x, gamma, eps):
    """(K, H) f32 rmsnorm, matching modules/norm.rms_norm numerics."""
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + eps) * gamma


def _rope_rows(x, cos, sin):
    """Half-rotation RoPE on (R, D) rows with (R, D/2) cos/sin
    (modules/rope.apply_rope convention)."""
    d2 = x.shape[-1] // 2
    x1 = x[:, :d2]
    x2 = x[:, d2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _attn_block_kernel(
    # scalar prefetch
    li_ref,  # (1,) layer index
    slots_ref,  # (B,) cache line per row
    tile_any_ref,  # (B, nkv) tile-skip bits
    # operands
    x_ref,  # (1, K, H) residual-stream input
    gamma_ref,  # (1, H)
    wqkv_ref,  # (H, TA) tile
    cos_ref,  # (1, K, D/2)
    sin_ref,  # (1, K, D/2)
    mask_ref,  # (1, 1, K, bs) pruned prior-mask tile
    k_ref,  # (1, 1, bs, Hkv, D) prior cache tile
    v_ref,
    wout_ref,  # (HqD, TC) tile
    # outputs
    o_ref,  # (1, K, H) hidden out (residual included)
    knew_ref,  # (1, K, Hkv, D) rope'd new K for the outside cache scatter
    vnew_ref,  # (1, K, Hkv, D)
    # scratch
    normed_scr,  # (K, H) f32
    qkv_scr,  # (K, N3) f32
    rows_scr,  # ((Hq+2Hkv)*K, D) f32 rep-major rows
    m_scr,  # (Hq*K, 1)
    l_scr,
    acc_scr,  # (Hq*K, D)
    attn_scr,  # (K, Hq*D) f32 finalized attention (t-major)
    *,
    scale: float,
    eps: float,
    K: int,
    Hq: int,
    Hkv: int,
    D: int,
    TA: int,
    TC: int,
    nA: int,
    nkv: int,
    nC: int,
    bs: int,
):
    b = pl.program_id(0)
    i = pl.program_id(1)
    n_rep = Hq // Hkv
    rk = n_rep * K
    HqK = Hq * K

    @pl.when(i == 0)
    def _init():
        x = x_ref[0].astype(jnp.float32)  # (K, H)
        normed_scr[:] = _rms(x, gamma_ref[0].astype(jnp.float32), eps)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # ---- phase A: QKV projection tile ----------------------------------
    @pl.when(i < nA)
    def _qkv():
        t = (
            jax.lax.dot_general(
                normed_scr[:].astype(wqkv_ref.dtype),
                wqkv_ref[:],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )  # (K, TA)
        qkv_scr[:, pl.ds(i * TA, TA)] = t

    # ---- step nA: rope + row relayout + new-KV emit + ACTIVE attention --
    @pl.when(i == nA)
    def _rope_active():
        cos = cos_ref[0].astype(jnp.float32)  # (K, D/2)
        sin = sin_ref[0].astype(jnp.float32)
        # rep-major q rows (row h*K + t) + rope'd k rows + v rows
        for h in range(Hq):
            qh = qkv_scr[:, h * D : (h + 1) * D]  # (K, D)
            rows_scr[h * K : (h + 1) * K, :] = _rope_rows(qh, cos, sin)
        for h in range(Hkv):
            kh = qkv_scr[:, (Hq + h) * D : (Hq + h + 1) * D]
            rows_scr[HqK + h * K : HqK + (h + 1) * K, :] = _rope_rows(kh, cos, sin)
        for h in range(Hkv):
            vh = qkv_scr[:, (Hq + Hkv + h) * D : (Hq + Hkv + h + 1) * D]
            rows_scr[HqK + Hkv * K + h * K : HqK + Hkv * K + (h + 1) * K, :] = vh
        # emit new K/V (the caller scatters them into the cache)
        for h in range(Hkv):
            knew_ref[0, :, h, :] = rows_scr[HqK + h * K : HqK + (h + 1) * K, :].astype(
                knew_ref.dtype
            )
            vnew_ref[0, :, h, :] = rows_scr[
                HqK + Hkv * K + h * K : HqK + Hkv * K + (h + 1) * K, :
            ].astype(vnew_ref.dtype)
        # active (in-flight) attention among the K new tokens, causal in t
        tri = (
            jax.lax.broadcasted_iota(jnp.int32, (rk, K), 0) % K
            >= jax.lax.broadcasted_iota(jnp.int32, (rk, K), 1)
        )
        for g in range(Hkv):
            rows = slice(g * rk, (g + 1) * rk)
            q = rows_scr[rows, :]
            k = rows_scr[HqK + g * K : HqK + (g + 1) * K, :]  # (K, D)
            v = rows_scr[HqK + Hkv * K + g * K : HqK + Hkv * K + (g + 1) * K, :]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
                )
                * scale
            )  # (rk, K)
            s = jnp.where(tri, s, NEG_INF)
            m_prev = m_scr[rows, :]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.where(tri, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_scr[rows, :] = l_scr[rows, :] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[rows, :] = acc_scr[rows, :] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            m_scr[rows, :] = m_new

    # ---- phase B: prior-cache attention tiles ---------------------------
    j = jnp.clip(i - nA, 0, nkv - 1)

    @pl.when((i >= nA) & (i < nA + nkv) & (tile_any_ref[b, j] > 0))
    def _prior():
        k_all = k_ref[0, 0].astype(jnp.float32)  # (bs, Hkv, D)
        v_all = v_ref[0, 0].astype(jnp.float32)
        mt = mask_ref[0, 0] > 0  # (K, bs)
        row_mask = jnp.repeat(mt[None], n_rep, axis=0).reshape(rk, bs)
        for g in range(Hkv):
            rows = slice(g * rk, (g + 1) * rk)
            q = rows_scr[rows, :]
            s = (
                jax.lax.dot_general(
                    q,
                    k_all[:, g, :],
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # (rk, bs)
            s = jnp.where(row_mask, s, NEG_INF)
            m_prev = m_scr[rows, :]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.where(row_mask, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_scr[rows, :] = l_scr[rows, :] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[rows, :] = acc_scr[rows, :] * alpha + jax.lax.dot_general(
                p,
                v_all[:, g, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_scr[rows, :] = m_new

    # ---- phase C: finalize + output projection + residual ---------------
    @pl.when(i == nA + nkv)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        out_rows = acc_scr[:] / denom  # (HqK, D)
        for h in range(Hq):
            attn_scr[:, h * D : (h + 1) * D] = out_rows[h * K : (h + 1) * K, :]

    @pl.when(i >= nA + nkv)
    def _oproj():
        cc = jnp.clip(i - nA - nkv, 0, nC - 1)
        t = jax.lax.dot_general(
            attn_scr[:].astype(wout_ref.dtype),
            wout_ref[:],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (K, TC)
        resid = x_ref[0, :, pl.ds(cc * TC, TC)].astype(jnp.float32)
        o_ref[0, :, pl.ds(cc * TC, TC)] = (resid + t).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "eps", "n_kv", "bs", "interpret"),
)
def fused_attn_block(
    x: jax.Array,  # (B, K, H) residual-stream input (pre-norm)
    gamma: jax.Array,  # (H,) input_layernorm weight
    wqkv: jax.Array,  # (H, (Hq+2Hkv)*D) fused QKV weight
    wout: jax.Array,  # (Hq*D, H) output projection weight
    cos: jax.Array,  # (B, K, D/2)
    sin: jax.Array,
    k_cache: jax.Array,  # (L, R, S_max, Hkv, D) FULL stacked cache
    v_cache: jax.Array,
    layer_idx: jax.Array,  # int32 scalar
    slot_ids: jax.Array,  # (B,) cache line per row
    mask: jax.Array,  # (B, 1, K, S_kv) decode mask INCLUDING current slots
    positions: jax.Array,  # (B, K) absolute positions of the new tokens
    *,
    scale: float,
    eps: float,
    n_kv: int,
    bs: int = None,
    interpret: bool = False,
):
    """Fused decode attention block. Returns (hidden (B,K,H) with residual
    added, k_new (B,K,Hkv,D), v_new (B,K,Hkv,D)); the caller scatters
    k_new/v_new into the cache (reference update_cache_in_kernel=False)."""
    B, K, H = x.shape
    Hkv = n_kv
    D = k_cache.shape[-1]
    N3 = wqkv.shape[1]
    Hq = N3 // D - 2 * Hkv
    HqD = Hq * D
    S_kv = mask.shape[-1]
    if bs is None:
        bs = tile_default("fused_attn_block", f"h{H}", x.dtype, "bs", 512)
    bs = min(bs, S_kv)
    nkv = S_kv // bs

    # tile widths trade per-step pipeline overhead against the ~16M
    # scoped-VMEM budget (TA=TC=512 at 1B shapes measured 16.27M — over);
    # TA=256/TC=512 keeps the big operand windows at 1M/2M double-buffered.
    # Caps read through the tuning table (KERN704); the while-loops stay as
    # the divisibility guard whatever the table says.
    TA = min(tile_default("fused_attn_block", f"h{H}", x.dtype, "ta_cap", 256), N3)
    while N3 % TA:
        TA //= 2
    nA = N3 // TA
    TC = min(tile_default("fused_attn_block", f"h{H}", x.dtype, "tc_cap", 512), H)
    while H % TC:
        TC //= 2
    nC = H // TC

    # prune the slots being written this step from the prior mask: the cache
    # scatter happens AFTER the kernel; the ACTIVE part covers those tokens
    cols = jnp.arange(S_kv, dtype=jnp.int32)[None, None, None, :]
    p0 = positions[:, 0][:, None, None, None]
    pruned = mask & ~((cols >= p0) & (cols < p0 + K))
    m, tile_any = _mask_tiles(pruned, nkv, bs)

    li = jnp.reshape(layer_idx, (1,)).astype(jnp.int32)
    kernel = functools.partial(
        _attn_block_kernel,
        scale=scale, eps=eps, K=K, Hq=Hq, Hkv=Hkv, D=D,
        TA=TA, TC=TC, nA=nA, nkv=nkv, nC=nC, bs=bs,
    )
    steps = nA + nkv + nC
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, steps),
        in_specs=[
            pl.BlockSpec((1, K, H), lambda b, i, li, sl, ta: (b, 0, 0)),
            pl.BlockSpec((1, H), lambda b, i, li, sl, ta: (0, 0)),
            pl.BlockSpec(
                (H, TA),
                lambda b, i, li, sl, ta, nA=nA: (0, jnp.clip(i, 0, nA - 1)),
            ),
            pl.BlockSpec((1, K, D // 2), lambda b, i, li, sl, ta: (b, 0, 0)),
            pl.BlockSpec((1, K, D // 2), lambda b, i, li, sl, ta: (b, 0, 0)),
            pl.BlockSpec(
                (1, 1, K, bs),
                lambda b, i, li, sl, ta, nA=nA, nkv=nkv: (
                    b, jnp.clip(i - nA, 0, nkv - 1), 0, 0,
                ),
            ),
            pl.BlockSpec(
                (1, 1, bs, Hkv, D),
                lambda b, i, li, sl, ta, nA=nA, nkv=nkv: (
                    li[0], sl[b], jnp.clip(i - nA, 0, nkv - 1), 0, 0,
                ),
            ),
            pl.BlockSpec(
                (1, 1, bs, Hkv, D),
                lambda b, i, li, sl, ta, nA=nA, nkv=nkv: (
                    li[0], sl[b], jnp.clip(i - nA, 0, nkv - 1), 0, 0,
                ),
            ),
            pl.BlockSpec(
                (HqD, TC),
                lambda b, i, li, sl, ta, nA=nA, nkv=nkv, nC=nC: (
                    0, jnp.clip(i - nA - nkv, 0, nC - 1),
                ),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, K, H), lambda b, i, li, sl, ta: (b, 0, 0)),
            pl.BlockSpec((1, K, Hkv, D), lambda b, i, li, sl, ta: (b, 0, 0, 0)),
            pl.BlockSpec((1, K, Hkv, D), lambda b, i, li, sl, ta: (b, 0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((K, H), jnp.float32),
            pltpu.VMEM((K, N3), jnp.float32),
            pltpu.VMEM(((Hq + 2 * Hkv) * K, D), jnp.float32),
            pltpu.VMEM((Hq * K, 1), jnp.float32),
            pltpu.VMEM((Hq * K, 1), jnp.float32),
            pltpu.VMEM((Hq * K, D), jnp.float32),
            pltpu.VMEM((K, HqD), jnp.float32),
        ],
    )
    out, k_new, v_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, K, H), x.dtype),
            jax.ShapeDtypeStruct((B, K, Hkv, D), x.dtype),
            jax.ShapeDtypeStruct((B, K, Hkv, D), x.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        li,
        slot_ids.astype(jnp.int32),
        tile_any,
        x,
        gamma.reshape(1, H),
        wqkv,
        cos,
        sin,
        m,
        k_cache,
        v_cache,
        wout,
    )
    return out, k_new, v_new


# ---------------------------------------------------------------------------
# fused MLP block
# ---------------------------------------------------------------------------


def _mlp_kernel(
    x_ref,  # (1, K, H)
    gamma_ref,  # (1, H)
    wg_ref,  # (H, TI)
    wu_ref,  # (H, TI)
    wd_ref,  # (TI, H)
    o_ref,  # (1, K, H)
    normed_scr,  # (K, H) f32
    acc_scr,  # (K, H) f32
    *,
    eps: float,
    nI: int,
    act: str,
):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        x = x_ref[0].astype(jnp.float32)
        normed_scr[:] = _rms(x, gamma_ref[0].astype(jnp.float32), eps)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    normed = normed_scr[:].astype(wg_ref.dtype)
    g = jax.lax.dot_general(
        normed, wg_ref[:], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    u = jax.lax.dot_general(
        normed, wu_ref[:], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    if act == "silu":
        a = jax.nn.silu(g) * u
    else:  # "gelu" / "gelu_pytorch_tanh" — models/base.act_fn maps BOTH to
        # the tanh approximation (jax.nn.gelu's default); the fused path must
        # match the native numerics exactly
        a = jax.nn.gelu(g, approximate=True) * u
    acc_scr[:] += jax.lax.dot_general(
        a.astype(wd_ref.dtype),
        wd_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == nI - 1)
    def _fin():
        o_ref[0] = (x_ref[0].astype(jnp.float32) + acc_scr[:]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "act", "interpret"))
def fused_mlp_block(
    x: jax.Array,  # (B, K, H) residual-stream input (pre-norm)
    gamma: jax.Array,  # (H,) post_attention_layernorm weight
    w_gate: jax.Array,  # (H, I)
    w_up: jax.Array,  # (H, I)
    w_down: jax.Array,  # (I, H)
    *,
    eps: float,
    act: str = "silu",
    interpret: bool = False,
):
    """Fused gated-MLP block for decode: returns x + down(act(gate) * up) of
    the rms-normed input, streaming each weight tile exactly once."""
    B, K, H = x.shape
    I = w_gate.shape[1]
    # the MLP kernel is its own pallas_call with its own VMEM budget: three
    # (·, TI) streams at TI=512 double-buffer to ~12M and halve the step
    # count (per-step pipeline overhead is the cost driver at K=1); the cap
    # reads through the tuning table (KERN704), the while-loop guards
    # divisibility
    TI = min(tile_default("fused_mlp_block", f"i{I}", x.dtype, "ti_cap", 512), I)
    while I % TI:
        TI //= 2
    nI = I // TI
    kernel = functools.partial(_mlp_kernel, eps=eps, nI=nI, act=act)
    return pl.pallas_call(
        kernel,
        grid=(B, nI),
        in_specs=[
            pl.BlockSpec((1, K, H), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, H), lambda b, i: (0, 0)),
            pl.BlockSpec((H, TI), lambda b, i: (0, i)),
            pl.BlockSpec((H, TI), lambda b, i: (0, i)),
            pl.BlockSpec((TI, H), lambda b, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, K, H), lambda b, i: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, K, H), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((K, H), jnp.float32),
            pltpu.VMEM((K, H), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x, gamma.reshape(1, H), w_gate, w_up, w_down)
