"""Fused MoE decode (TKG) kernel: stream ONLY the selected experts' weights.

TPU-native re-design of the reference's fused MoE token-generation kernels
(reference: moe_fused_nki_kernel_enabled + MoEFusedTKGConfig, moe_v2.py:105;
the NKI expert-MLP tokengen kernels of §2.10).

Why a kernel: at decode (T = batch*spec_len tokens, tiny) the native
all-experts path (modules/moe.expert_mlps_dense) reads EVERY expert's
gate/up/down weights from HBM — E/k times more weight traffic than the
tokens mathematically need. XLA cannot gather whole weight matrices by a
traced expert index without materializing; a Pallas kernel CAN: the per-row
expert id rides scalar prefetch and the BlockSpec index map DMAs exactly the
selected expert's weight tiles (the same trick the paged-attention kernels
use for cache blocks). HBM traffic drops to k/E of the dense path — 4x for
Mixtral (2/8), 32x for DeepSeek-V3 routed experts (8/256).

Grid: (T*k, nI). Row r = token t = r//k, selection j = r%k, expert
e = topk_idx[t, j] (prefetch). Each step streams one (H, TI) gate tile, one
(H, TI) up tile and one (TI, H) down tile of expert e:
acc += glu(x_t @ Wg_e[:, tile], x_t @ Wu_e[:, tile]) @ Wd_e[tile, :].
The (T, k, H) per-selection outputs are combined with the routing weights
outside (a tiny einsum).

AUTO=OFF like the other decode-layer kernels until hardware measurement
flips it (config moe_fused_kernel_enabled: None=off, True=force, False=off).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from neuronx_distributed_inference_tpu.ops.tile_defaults import tile_default

try:  # pallas TPU backend
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


# kernel/native dispatch gate: consolidated in ops/kernel_mode.py (one
# tested predicate per kernel); the historical name stays importable here
from neuronx_distributed_inference_tpu.ops.kernel_mode import (  # noqa: E402
    use_moe_tkg as use_moe_tkg_kernel,
)


def _moe_kernel(
    # scalar prefetch
    e_ref,  # (T*k,) expert id per row
    # blocked operands (x/o carry a dummy middle axis: a (1, H) block over a
    # (rows, H) array violates Mosaic's last-two-dims rule for rows > 1)
    x_ref,  # (1, 1, H) token activations for this row
    wg_ref,  # (1, H, TI) selected expert's gate tile
    wu_ref,  # (1, H, TI)
    wd_ref,  # (1, TI, H)
    o_ref,  # (1, 1, H)
    acc_scr,  # (1, H) f32
    *,
    nI: int,
    act: str,
    act_scale: float,
    act_bias: float,
    swiglu_limit: Optional[float],
):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    x = x_ref[0].astype(jnp.float32)  # (1, H)
    g = jax.lax.dot_general(
        x.astype(wg_ref.dtype), wg_ref[0],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )  # (1, TI)
    u = jax.lax.dot_general(
        x.astype(wu_ref.dtype), wu_ref[0],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    if act_scale != 1.0 or act_bias != 0.0 or swiglu_limit is not None:
        # GPT-OSS clamped swiglu (modules/moe._glu_fn)
        if swiglu_limit is not None:
            g = jnp.clip(g, max=swiglu_limit)
            u = jnp.clip(u, -swiglu_limit, swiglu_limit)
        a = g * jax.nn.sigmoid(act_scale * g) * (u + act_bias)
    elif act == "silu":
        a = jax.nn.silu(g) * u
    else:  # gelu family (models/base.act_fn maps both to tanh-approx)
        a = jax.nn.gelu(g, approximate=True) * u
    acc_scr[:] += jax.lax.dot_general(
        a.astype(wd_ref.dtype), wd_ref[0],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(i == nI - 1)
    def _fin():
        o_ref[0] = acc_scr[:].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("act", "act_scale", "act_bias", "swiglu_limit", "interpret"),
)
def fused_moe_decode(
    x: jax.Array,  # (T, H)
    topk_idx: jax.Array,  # (T, k) selected expert per token
    topk_w: jax.Array,  # (T, k) combine weights
    w_gate: jax.Array,  # (E, H, I)
    w_up: jax.Array,  # (E, H, I)
    w_down: jax.Array,  # (E, I, H)
    *,
    act: str = "silu",
    act_scale: float = 1.0,
    act_bias: float = 0.0,
    swiglu_limit: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """Selected-experts-only MoE decode: returns (T, H) combined output."""
    T, H = x.shape
    k = topk_idx.shape[1]
    E, _, I = w_gate.shape
    # three double-buffered weight windows must fit the ~16M scoped VMEM;
    # the starting cap reads through the tuning table (KERN704) and the
    # while-loop remains the VMEM-fit + divisibility guard regardless of
    # what the table says
    itemsize = jnp.dtype(w_gate.dtype).itemsize
    TI = tile_default("fused_moe_decode", f"h{H}_i{I}", w_gate.dtype, "ti_cap", 512)
    while TI > 16 and (H * TI * itemsize * 2 * 3 > 11 << 20 or I % TI):
        TI //= 2
    if I % TI:
        raise ValueError(
            f"expert intermediate size {I} is not tileable (needs a divisor "
            f"<= {TI} that is a multiple of 16); use the dense MoE path"
        )
    nI = I // TI

    e_flat = topk_idx.reshape(T * k).astype(jnp.int32)
    kernel = functools.partial(
        _moe_kernel, nI=nI, act=act, act_scale=act_scale, act_bias=act_bias,
        swiglu_limit=swiglu_limit,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T * k, nI),
        in_specs=[
            pl.BlockSpec((1, 1, H), lambda r, i, e, k=k: (r // k, 0, 0)),
            pl.BlockSpec((1, H, TI), lambda r, i, e: (e[r], 0, i)),
            pl.BlockSpec((1, H, TI), lambda r, i, e: (e[r], 0, i)),
            pl.BlockSpec((1, TI, H), lambda r, i, e: (e[r], i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, H), lambda r, i, e: (r, 0, 0)),
        scratch_shapes=[pltpu.VMEM((1, H), jnp.float32)],
    )
    per_sel = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T * k, 1, H), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(e_flat, x[:, None, :], w_gate, w_up, w_down)
    per_sel = per_sel.reshape(T, k, H)
    return jnp.einsum(
        "tk,tkh->th", topk_w.astype(jnp.float32), per_sel.astype(jnp.float32)
    ).astype(x.dtype)
