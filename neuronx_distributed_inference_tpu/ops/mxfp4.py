"""MXFP4 (microscaling fp4) dequantization for GPT-OSS expert weights.

Reference: models/gpt_oss/mx_layout_transform.py — the reference re-lays-out
MXFP4 blocks/scales for its NKI kernels; on TPU we DEQUANTIZE to the compute
dtype at load (the MoE matmuls then run bf16 on the MXU; int8/blockwise
re-quantization can be layered on via the standard quantization path).

Format (HF gpt-oss checkpoints, transformers.integrations.mxfp4):
- ``*_blocks``: uint8 (..., G, B), two e2m1 fp4 values per byte (low nibble
  first);
- ``*_scales``: uint8 (..., G), e8m0 shared exponents biased by 127.
Dequantized logical tensor = (..., G*B*2) then the last two logical dims
swap — (E, rows, cols) packed becomes the (E, cols, rows) plain weight.
"""

from __future__ import annotations

import numpy as np

# e2m1 value table (transformers.integrations.mxfp4.FP4_VALUES)
FP4_VALUES = np.array(
    [
        +0.0, +0.5, +1.0, +1.5, +2.0, +3.0, +4.0, +6.0,
        -0.0, -0.5, -1.0, -1.5, -2.0, -3.0, -4.0, -6.0,
    ],
    dtype=np.float32,
)


def dequantize_mxfp4(blocks: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """(..., G, B) uint8 blocks + (..., G) uint8 scales -> (..., cols, rows)
    float32, matching transformers' convert_moe_packed_tensors (including the
    trailing transpose to the plain-weight layout)."""
    blocks = np.asarray(blocks, np.uint8)
    scales = np.asarray(scales).astype(np.int32) - 127
    if blocks.shape[:-1] != scales.shape:
        raise ValueError(f"blocks {blocks.shape} do not match scales {scales.shape}")

    lo = FP4_VALUES[blocks & 0x0F]
    hi = FP4_VALUES[blocks >> 4]
    out = np.empty(blocks.shape[:-1] + (blocks.shape[-1] * 2,), np.float32)
    out[..., 0::2] = lo
    out[..., 1::2] = hi
    out *= np.exp2(scales)[..., None].astype(np.float32)
    *prefix, G, B2 = out.shape
    out = out.reshape(*prefix[:-1], prefix[-1], G * B2)  # (E, rows, cols)
    return np.swapaxes(out, -2, -1)  # (E, cols, rows) — the plain layout


def dequantize_packed_state_dict(sd: dict) -> dict:
    """Replace every ``<name>_blocks``/``<name>_scales`` pair in an HF state
    dict with the dequantized plain ``<name>`` tensor."""
    sd = dict(sd)
    packed = [k[: -len("_blocks")] for k in sd if k.endswith("_blocks")]
    for name in packed:
        blocks = sd.pop(name + "_blocks")
        scales = sd.pop(name + "_scales")
        sd[name] = dequantize_mxfp4(blocks, scales)
    return sd


def repack_mxfp4_to_int4(blocks: np.ndarray, scales: np.ndarray, group_size: int = 128):
    """MXFP4 -> grouped-int4 runtime repack (per-tensor primitive).

    e2m1 mantissas on a shared e8m0 exponent cannot map exactly onto a
    single-scale int4 grid (block values {0, .5, 1, 1.5, 2, 3, 4, 6}·2^e span
    12 steps of the finest spacing but int4 carries 7), so the repack is
    dequantize -> per-(group, out) absmax REQUANTIZE. Relative error stays
    bounded by the int4 step (~scale/2 per element, ~7% worst-case on e2m1
    extremes — measured in tests/test_quant_matmul.py); in exchange the
    expert streams at 0.5 byte/param through the same grouped-int4 path as
    every other weight instead of needing an MXFP4-specific kernel.

    In the serving flow this composes as load-time dequant
    (``dequantize_packed_state_dict``) + the ``weight_dtype="int4"``
    quantize walk — this function is that composition for ONE tensor,
    used where an expert must repack without staging the whole model."""
    from neuronx_distributed_inference_tpu.ops.quant_matmul import (
        quantize_tensor_int4,
    )

    # (E, cols, rows) plain layout = (E, in, out) for gate/up; callers feed
    # whatever orientation their consumer expects — the quantize groups the
    # -2 axis either way
    return quantize_tensor_int4(dequantize_mxfp4(blocks, scales), group_size)
