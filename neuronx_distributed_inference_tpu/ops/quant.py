"""Weight quantization: int8 / fp8, per-channel / per-tensor symmetric.

TPU-native re-design of the reference quantization flow
(reference: quantized checkpoint generation application_base.py:744-797;
nxd quantization.convert() applied in DecoderModelInstance,
model_wrapper.py:1589-1671; QuantizedColumn/RowParallel layers).

Quantized linears store ``{"weight": int8/fp8 (..., in, out), "scale":
(..., out) or (..., 1)}``. The matmul runs in the activation dtype with the
per-output-channel scale applied AFTER the matmul — exact for symmetric
per-channel(out) scales, and XLA fuses the cast+scale into the matmul.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

QUANT_DTYPES = {
    "int8": jnp.int8,
    "fp8": jnp.float8_e4m3fn,
    "float8_e4m3": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}

# param-tree keys never quantized (reference modules_to_not_convert defaults)
# Reference posture: modules_to_not_convert defaults to None there, i.e.
# EVERY Linear converts — including lm_head (config.py:219). Measured here
# (PERF.md r5): the bf16 lm_head was 30% of the int8-1B decode step's
# device time; quantizing it is +11% decode throughput. Norm/router/sink/
# embed stay excluded (not weight-streamed matmuls / accuracy-critical).
DEFAULT_SKIP = ("embed_tokens", "rope", "norm", "input_layernorm",
                "post_attention_layernorm", "q_norm", "k_norm", "router", "sink")


def quantize_tensor(
    w: jax.Array,
    quant_dtype: str = "int8",
    per_channel: bool = True,
):
    """Symmetric quantization along the last (output) axis.

    Returns {"weight": q, "scale": s} with w ≈ q * s.

    Host (numpy) inputs quantize WITH numpy and return numpy — quantize-at-load
    of models near the HBM limit (int8 8B on a 16G chip) must not stage the
    fp32 intermediate on device; ``shard_pytree`` device-puts the int8 result.
    """
    dt = QUANT_DTYPES[quant_dtype]
    xp = np if isinstance(w, np.ndarray) else jnp
    wf = w.astype(xp.float32)
    if per_channel:
        # reduce ONLY the input axis (-2): stacked-layer / stacked-expert
        # weights (L, ..., in, out) keep one scale per (leading dims, out)
        absmax = xp.max(xp.abs(wf), axis=-2)  # (..., out)
    else:
        # per-tensor per leading slice: reduce the last two axes
        absmax = xp.max(xp.abs(wf), axis=(-2, -1), keepdims=True)[..., 0]  # (..., 1)
    absmax = xp.maximum(absmax, 1e-8)
    qmax = 127.0 if dt == jnp.int8 else float(jnp.finfo(dt).max)
    scale = absmax / qmax
    if xp is np:
        # host path: mutate the fp32 upcast IN PLACE so peak transient per
        # leaf is (source) + (one fp32 copy) + (quantized result) — not two
        # fp32 copies; the near-RAM-limit 8B quantize-at-load depends on it
        import ml_dtypes  # numpy fp8/bf16 dtype support

        wf /= scale[..., None, :]
        if dt == jnp.int8:
            np.rint(wf, out=wf)
            np.clip(wf, -127, 127, out=wf)
        np_dt = np.int8 if dt == jnp.int8 else np.dtype(ml_dtypes.float8_e4m3fn if dt == jnp.float8_e4m3fn else ml_dtypes.float8_e5m2)
        return {"weight": wf.astype(np_dt), "scale": scale.astype(np.float32)}
    q = wf / scale[..., None, :]
    if dt == jnp.int8:
        q = jnp.clip(jnp.round(q), -127, 127)
    return {"weight": q.astype(dt), "scale": scale.astype(jnp.float32)}


def quantize_tensor_blockwise(
    w: jax.Array,
    quant_dtype: str = "int8",
    block_size: int = 128,
):
    """Symmetric BLOCKWISE quantization: the input axis (-2) is split into
    blocks of ``block_size``, one scale per (block, out_channel)
    (reference blockwise quantization path + blockwise_matmul_block_size,
    MoENeuronConfig config.py:665-713).

    Returns {"weight": q (..., in, out), "scale": s (..., in/bs, out)}.
    Numpy inputs stay on host (see quantize_tensor).
    """
    dt = QUANT_DTYPES[quant_dtype]
    xp = np if isinstance(w, np.ndarray) else jnp
    wf = w.astype(xp.float32)
    *lead, d_in, d_out = wf.shape
    if d_in % block_size != 0:
        raise ValueError(
            f"blockwise quantization needs in-dim {d_in} divisible by "
            f"block_size {block_size}"
        )
    nb = d_in // block_size
    wb = wf.reshape(*lead, nb, block_size, d_out)
    absmax = xp.maximum(xp.max(xp.abs(wb), axis=-2), 1e-8)  # (..., nb, out)
    qmax = 127.0 if dt == jnp.int8 else float(jnp.finfo(dt).max)
    scale = absmax / qmax
    q = wb / scale[..., None, :]
    if dt == jnp.int8:
        q = xp.clip(xp.round(q), -127, 127)
    if xp is np:
        import ml_dtypes

        np_dt = np.int8 if dt == jnp.int8 else np.dtype(ml_dtypes.float8_e4m3fn if dt == jnp.float8_e4m3fn else ml_dtypes.float8_e5m2)
        return {
            "weight": q.astype(np_dt).reshape(*lead, d_in, d_out),
            "scale": scale.astype(np.float32),
        }
    return {
        "weight": q.astype(dt).reshape(*lead, d_in, d_out),
        "scale": scale.astype(jnp.float32),
    }


def is_quantized_leaf(entry: dict) -> bool:
    return isinstance(entry, dict) and "scale" in entry and "weight" in entry


def linear(entry: dict, x: jax.Array) -> jax.Array:
    """Apply a (possibly quantized) linear weight: x @ W [+ dequant scale].

    Used by every projection so quantization is transparent to model code
    (reference: layer swap to Quantized*Parallel in convert()). Blockwise
    scales (one per input block per out channel — scale.ndim == w.ndim) apply
    per-block partial sums; per-channel/per-tensor scales apply after the
    full matmul.
    """
    w = entry["weight"]
    if jnp.dtype(w.dtype) == jnp.uint8:
        # packed grouped-int4 (ops/quant_matmul): uint8 is the structural
        # discriminator — scale.ndim matches the blockwise case but the
        # weight rows are nibble-packed codes, so it must dispatch FIRST.
        # Decode-shaped calls stream through the fused-dequant Pallas kernel
        # (gated in ops/kernel_mode.use_quant_matmul; interpreted on CPU when
        # forced); everything else — prefill, sharded meshes, odd shapes —
        # takes the group-structured native path.
        from neuronx_distributed_inference_tpu.ops import quant_matmul as _qmm
        from neuronx_distributed_inference_tpu.ops.kernel_mode import (
            kernel_interpret,
            use_quant_matmul,
        )

        s = entry["scale"]
        rows = 1
        for d in x.shape[:-1]:
            rows *= d
        group = (2 * w.shape[-2]) // s.shape[-2]
        if w.ndim == 2 and use_quant_matmul(rows, x.shape[-1], w.shape[-1], group):
            return _qmm.quant_matmul(x, w, s, interpret=kernel_interpret())
        return _qmm.int4_matmul_native(x, w, s)
    if "scale" in entry:
        s = entry["scale"]
        if s.ndim == w.ndim:  # blockwise: (..., nb, out) for w (..., in, out)
            nb = s.shape[-2]
            bs = w.shape[-2] // nb
            xb = x.reshape(*x.shape[:-1], nb, bs)
            wb = w.reshape(*w.shape[:-2], nb, bs, w.shape[-1])
            # per-block partial products, scaled per block, summed — exact
            # dequantized matmul
            y = jnp.einsum("...nb,nbo->...no", xb, wb.astype(x.dtype))
            return jnp.einsum("...no,no->...o", y, s.astype(x.dtype))
        y = x @ w.astype(x.dtype)
        return y * s.astype(x.dtype)
    return x @ w


def quantize_params(
    params: dict,
    quant_dtype: str = "int8",
    block_size: int = 0,
    per_channel: bool = True,
    skip: Sequence[str] = DEFAULT_SKIP,
    min_ndim: int = 2,
):
    """Walk the param pytree quantizing every eligible 'weight' leaf.

    DONATING: the tree is mutated in place and each source weight's reference
    is dropped as soon as its quantized replacement exists. Peak transient
    memory per in-flight leaf is (source leaf) + (one fp32 upcast, mutated in
    place) + (quantized result); the host path runs ``TPU_QUANT_WORKERS``
    (default 2) leaves concurrently, the serial/device path exactly one —
    never two full models. An int8 8B quantize-at-load on a 16G chip depends
    on this bound.

    Reference: save_quantized_state_dict / convert()
    (application_base.py:744-797).
    """

    eligible = []

    def walk(node, path):
        if isinstance(node, dict):
            if (
                "weight" in node
                and "scale" not in node
                and not any(s in path for s in skip)
                and hasattr(node["weight"], "ndim")
                and node["weight"].ndim >= min_ndim
                and "bias" not in path
            ):
                eligible.append(node)
                return node
            for k in list(node):
                node[k] = walk(node[k], path + (k,))
            return node
        return node

    walk(params, ())

    def quantize_one(node):
        if quant_dtype == "int4":
            from neuronx_distributed_inference_tpu.ops.quant_matmul import (
                INT4_GROUP,
                quantize_tensor_int4,
            )

            q = quantize_tensor_int4(node["weight"], block_size or INT4_GROUP)
        elif block_size:
            q = quantize_tensor_blockwise(node["weight"], quant_dtype, block_size)
        else:
            q = quantize_tensor(node["weight"], quant_dtype, per_channel)
        node["weight"] = q["weight"]  # drops the source weight's last reference
        node["scale"] = q["scale"]

    host = bool(eligible) and isinstance(eligible[0]["weight"], np.ndarray)
    workers = int(os.environ.get("TPU_QUANT_WORKERS", "2"))
    if host and len(eligible) > 1 and workers > 1:
        # host quantize-at-load: leaves are independent and numpy releases
        # the GIL — a small pool cuts a multi-core 8B walk severalfold
        # (VERDICT r4 weak #2); see the docstring for the per-worker
        # transient-memory bound that sizes the default
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as ex:
            list(ex.map(quantize_one, eligible))
    else:
        for node in eligible:
            quantize_one(node)
    return params


def prepare_quantized_params(params: dict, pspecs: dict, tpu_config):
    """Quantize-at-load for any application: returns (params, pspecs) with
    scale leaves added (reference quantized state-dict generation,
    application_base.py:744-797). Shared by the causal-lm and fused-spec
    loaders so the feature can't drift between them."""
    blockwise = tpu_config.quantization_type == "blockwise"
    skip = (
        tuple(tpu_config.modules_to_not_convert)
        if tpu_config.modules_to_not_convert
        else DEFAULT_SKIP
    )
    params = quantize_params(
        params,
        tpu_config.quantization_dtype,
        per_channel=tpu_config.quantization_type != "per_tensor_symmetric",
        skip=skip,
        block_size=(tpu_config.blockwise_matmul_block_size if blockwise else 0),
    )
    return params, quantized_pspecs(pspecs, params)


def prepare_int4_params(params: dict, pspecs: dict, tpu_config):
    """``weight_dtype="int4"`` quantize-at-load: packs every eligible weight
    leaf to the ops/quant_matmul grouped-int4 format (uint8 nibble codes +
    per-(group, out) f32 scales) and mirrors pspecs onto the added scale
    leaves. Same walk/skip-set/donation discipline as the int8 path —
    weights stream from HBM at 0.5 byte/param in decode (docs/WEIGHT_QUANT.md)."""
    from neuronx_distributed_inference_tpu.ops.quant_matmul import INT4_GROUP

    skip = (
        tuple(tpu_config.modules_to_not_convert)
        if tpu_config.modules_to_not_convert
        else DEFAULT_SKIP
    )
    params = quantize_params(params, "int4", skip=skip, block_size=INT4_GROUP)
    return params, _int4_output_sharded_pspecs(
        quantized_pspecs(pspecs, params), params
    )


def _int4_output_sharded_pspecs(pspecs: dict, qparams: dict) -> dict:
    """Grouped-int4 entries must shard on the OUTPUT axis only (the AWQ/GPTQ
    tensor-parallel convention): the group structure spans global K, so an
    input-axis shard splits groups across devices and every decode step
    re-gathers the packed codes inside the loop (GRAPH303). Rewrite any
    input-sharded int4 weight/scale spec to put that mesh axis on the output
    dim instead — weight bytes stay 1/tp per device; resharding moves to the
    (much smaller) decode activations."""
    from jax.sharding import PartitionSpec as P

    from neuronx_distributed_inference_tpu.ops.quant_matmul import is_int4_entry

    def walk(spec_node, param_node):
        if isinstance(param_node, dict) and is_int4_entry(param_node):
            if not isinstance(spec_node, dict):
                return spec_node
            parts = tuple(spec_node.get("weight") or P())
            if len(parts) < 2 or parts[-2] is None:
                return spec_node  # already output-only (or replicated)
            out_ax = parts[-1] if parts[-1] is not None else parts[-2]
            moved = P(*(parts[:-2] + (None, out_ax)))
            out = dict(spec_node)
            out["weight"] = moved
            out["scale"] = moved
            return out
        if isinstance(param_node, dict):
            return {
                k: walk(spec_node.get(k) if isinstance(spec_node, dict) else spec_node, v)
                for k, v in param_node.items()
            }
        return spec_node

    return walk(pspecs, qparams)


def quantized_pspecs(pspecs: dict, qparams: dict) -> dict:
    """Mirror a PartitionSpec tree onto a quantized param tree: every added
    'scale' leaf gets the weight's output-axis sharding (lead axes kept, the
    input axis dropped); per-tensor scales (last dim 1) replicate."""
    from jax.sharding import PartitionSpec as P

    def walk(spec_node, param_node):
        if isinstance(param_node, dict) and is_quantized_leaf(param_node):
            wspec = spec_node["weight"] if isinstance(spec_node, dict) else P()
            parts = tuple(wspec)
            blockwise = param_node["scale"].ndim == param_node["weight"].ndim
            if len(parts) >= 2 and blockwise:
                # (..., nb, out): block axis unsharded, out follows the weight
                out_axis = parts[-1] if param_node["scale"].shape[-1] > 1 else None
                scale_spec = P(*(parts[:-2] + (None, out_axis)))
            elif len(parts) >= 2:
                out_axis = parts[-1] if param_node["scale"].shape[-1] > 1 else None
                scale_spec = P(*(parts[:-2] + (out_axis,)))
            else:
                scale_spec = P()
            out = dict(spec_node)
            out["scale"] = scale_spec
            return out
        if isinstance(param_node, dict):
            return {
                k: walk(spec_node.get(k) if isinstance(spec_node, dict) else spec_node, v)
                for k, v in param_node.items()
            }
        return spec_node

    return walk(pspecs, qparams)


# ---------------------------------------------------------------------------
# quantized checkpoint save/load (reference save_quantized_state_dict +
# quantized_checkpoints_path reload, application_base.py:636-797)
# ---------------------------------------------------------------------------

QUANT_CKPT_FILE = "quantized_model.safetensors"


def _flatten_params(params, prefix=""):
    flat = {}
    if isinstance(params, dict):
        for k, v in params.items():
            flat.update(_flatten_params(v, f"{prefix}{k}."))
    elif isinstance(params, (list, tuple)):
        for i, v in enumerate(params):
            flat.update(_flatten_params(v, f"{prefix}{i}#."))
    else:
        import numpy as np

        flat[prefix[:-1]] = np.asarray(params)
    return flat


def _unflatten_params(flat):
    root = {}
    for key, v in flat.items():
        node = root
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v

    def listify(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.endswith("#") and k[:-1].isdigit() for k in node):
            return [listify(node[k]) for k in sorted(node, key=lambda s: int(s[:-1]))]
        return {k: listify(v) for k, v in node.items()}

    return listify(root)


def _quant_meta(tpu_config) -> dict:
    return {
        "quantization_type": tpu_config.quantization_type,
        "quantization_dtype": tpu_config.quantization_dtype,
        "weight_dtype": getattr(tpu_config, "weight_dtype", "bfloat16"),
        "blockwise_matmul_block_size": tpu_config.blockwise_matmul_block_size,
        # WHICH modules were converted is part of the recipe: an artifact
        # saved under an old skip set (e.g. bf16 lm_head) must re-quantize,
        # not silently serve the old tree
        "modules_to_not_convert": sorted(
            tpu_config.modules_to_not_convert
            if tpu_config.modules_to_not_convert
            else DEFAULT_SKIP
        ),
    }


def save_quantized_checkpoint(params: dict, path: str, tpu_config=None):
    """Persist an (already quantized) param pytree so future loads skip the
    convert+quantize work (reference save_quantized_state_dict,
    application_base.py:745-768). List-valued layer groups flatten with
    ``<idx>#`` path segments; a meta json records the quantization recipe so
    stale artifacts are detected."""
    import json
    import os

    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    save_file(_flatten_params(params), os.path.join(path, QUANT_CKPT_FILE))
    if tpu_config is not None:
        with open(os.path.join(path, "quantization.json"), "w") as f:
            json.dump(_quant_meta(tpu_config), f)


def load_quantized_checkpoint(path: str) -> dict:
    """Load a pre-quantized checkpoint back into the param pytree (reference
    quantized_checkpoints_path load, application_base.py:636-643)."""
    import os

    from safetensors.numpy import load_file

    return _unflatten_params(load_file(os.path.join(path, QUANT_CKPT_FILE)))


def has_quantized_checkpoint(path, tpu_config=None) -> bool:
    """True when a usable artifact exists AND (if a config is given) its
    recorded quantization recipe matches — a stale recipe re-quantizes."""
    import json
    import os

    if not path or not os.path.exists(os.path.join(path, QUANT_CKPT_FILE)):
        return False
    if tpu_config is None:
        return True
    meta_path = os.path.join(path, "quantization.json")
    if not os.path.exists(meta_path):
        return False
    with open(meta_path) as f:
        return json.load(f) == _quant_meta(tpu_config)
