"""Weight quantization: int8 / fp8, per-channel / per-tensor symmetric.

TPU-native re-design of the reference quantization flow
(reference: quantized checkpoint generation application_base.py:744-797;
nxd quantization.convert() applied in DecoderModelInstance,
model_wrapper.py:1589-1671; QuantizedColumn/RowParallel layers).

Quantized linears store ``{"weight": int8/fp8 (..., in, out), "scale":
(..., out) or (..., 1)}``. The matmul runs in the activation dtype with the
per-output-channel scale applied AFTER the matmul — exact for symmetric
per-channel(out) scales, and XLA fuses the cast+scale into the matmul.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

QUANT_DTYPES = {
    "int8": jnp.int8,
    "fp8": jnp.float8_e4m3fn,
    "float8_e4m3": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}

# param-tree keys never quantized (reference modules_to_not_convert defaults)
DEFAULT_SKIP = ("embed_tokens", "rope", "norm", "input_layernorm",
                "post_attention_layernorm", "q_norm", "k_norm", "router", "sink",
                "lm_head")


def quantize_tensor(
    w: jax.Array,
    quant_dtype: str = "int8",
    per_channel: bool = True,
):
    """Symmetric quantization along the last (output) axis.

    Returns {"weight": q, "scale": s} with w ≈ q * s.
    """
    dt = QUANT_DTYPES[quant_dtype]
    wf = w.astype(jnp.float32)
    if per_channel:
        # reduce ONLY the input axis (-2): stacked-layer / stacked-expert
        # weights (L, ..., in, out) keep one scale per (leading dims, out)
        absmax = jnp.max(jnp.abs(wf), axis=-2)  # (..., out)
    else:
        # per-tensor per leading slice: reduce the last two axes
        absmax = jnp.max(jnp.abs(wf), axis=(-2, -1), keepdims=True)[..., 0]  # (..., 1)
    absmax = jnp.maximum(absmax, 1e-8)
    qmax = 127.0 if dt == jnp.int8 else float(jnp.finfo(dt).max)
    scale = absmax / qmax
    q = wf / scale[..., None, :]
    if dt == jnp.int8:
        q = jnp.clip(jnp.round(q), -127, 127)
    return {"weight": q.astype(dt), "scale": scale.astype(jnp.float32)}


def is_quantized_leaf(entry: dict) -> bool:
    return isinstance(entry, dict) and "scale" in entry and "weight" in entry


def linear(entry: dict, x: jax.Array) -> jax.Array:
    """Apply a (possibly quantized) linear weight: x @ W [+ dequant scale].

    Used by every projection so quantization is transparent to model code
    (reference: layer swap to Quantized*Parallel in convert()).
    """
    w = entry["weight"]
    if "scale" in entry:
        y = x @ w.astype(x.dtype)
        return y * entry["scale"].astype(x.dtype)
    return x @ w


def quantize_params(
    params: dict,
    quant_dtype: str = "int8",
    per_channel: bool = True,
    skip: Sequence[str] = DEFAULT_SKIP,
    min_ndim: int = 2,
):
    """Walk the param pytree quantizing every eligible 'weight' leaf.

    Reference: save_quantized_state_dict / convert()
    (application_base.py:744-797).
    """

    def walk(node, path):
        if isinstance(node, dict):
            if (
                "weight" in node
                and "scale" not in node
                and not any(s in path for s in skip)
                and hasattr(node["weight"], "ndim")
                and node["weight"].ndim >= min_ndim
                and "bias" not in path
            ):
                out = dict(node)
                out.update(quantize_tensor(node["weight"], quant_dtype, per_channel))
                return out
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    return walk(params, ())


def prepare_quantized_params(params: dict, pspecs: dict, tpu_config):
    """Quantize-at-load for any application: returns (params, pspecs) with
    scale leaves added (reference quantized state-dict generation,
    application_base.py:744-797). Shared by the causal-lm and fused-spec
    loaders so the feature can't drift between them."""
    if tpu_config.quantization_type == "blockwise":
        raise NotImplementedError(
            "blockwise quantization is configured but not implemented yet; "
            "use per_channel_symmetric or per_tensor_symmetric"
        )
    skip = (
        tuple(tpu_config.modules_to_not_convert)
        if tpu_config.modules_to_not_convert
        else DEFAULT_SKIP
    )
    params = quantize_params(
        params,
        tpu_config.quantization_dtype,
        per_channel=tpu_config.quantization_type != "per_tensor_symmetric",
        skip=skip,
    )
    return params, quantized_pspecs(pspecs, params)


def quantized_pspecs(pspecs: dict, qparams: dict) -> dict:
    """Mirror a PartitionSpec tree onto a quantized param tree: every added
    'scale' leaf gets the weight's output-axis sharding (lead axes kept, the
    input axis dropped); per-tensor scales (last dim 1) replicate."""
    from jax.sharding import PartitionSpec as P

    def walk(spec_node, param_node):
        if isinstance(param_node, dict) and is_quantized_leaf(param_node):
            wspec = spec_node["weight"] if isinstance(spec_node, dict) else P()
            parts = tuple(wspec)
            if len(parts) >= 2:
                out_axis = parts[-1] if param_node["scale"].shape[-1] > 1 else None
                scale_spec = P(*(parts[:-2] + (out_axis,)))
            else:
                scale_spec = P()
            out = dict(spec_node)
            out["scale"] = scale_spec
            return out
        if isinstance(param_node, dict):
            return {
                k: walk(spec_node.get(k) if isinstance(spec_node, dict) else spec_node, v)
                for k, v in param_node.items()
            }
        return spec_node

    return walk(pspecs, qparams)
