"""Pallas kernel execution-mode switch + the consolidated dispatch gates.

Off-TPU hosts run every Pallas kernel in interpret mode (pure-Python
emulation) so the CPU test mesh exercises kernel numerics. That also means no
CPU test can ever hit a **Mosaic lowering** error — the class of bug that
breaks only on hardware (r1 ``_pick_chunk``; r3 the flash ``key_valid``
BlockSpec). :func:`force_compiled_kernels` flips the wrappers to emit real
Mosaic kernels regardless of host backend, so the suite can AOT-lower every
kernel (and whole model programs) for the TPU target from a CPU host via
``jax.export(..., platforms=["tpu"])`` — see tests/test_tpu_lowering.py.

Dispatch gates
--------------
Every kernel/native auto-gate lives HERE, one tested predicate per kernel
(tests/test_kernel_mode.py), instead of being scattered across the kernel
modules: the gates share the same tri-state convention (config None = auto,
True = force with shape guards + a warning on fallback, False = off) and a
change to one kernel's auto condition must not silently flip another's.
The kernel modules re-export their historical names (``_use_flash``,
``use_tkg_kernel``, ...) as aliases of these predicates.

Gate summary (auto path):

============================  ==============================================
kernel                        auto condition beyond the shape guards
============================  ==============================================
flash / packed prefill        single model-parallel shard, TPU backend
paged flash prefill           single shard, TPU, q_len >= 64
TKG decode (contig + paged)   single shard, TPU, kv_width >= 512
fused MoE decode              OFF (force-only pending hardware wins)
ragged mixed-step             TPU backend — **sharded meshes included**:
                              the mixed step wraps the kernel in
                              ``shard_map`` over the head-parallel grid
                              axis, so tp>1 no longer forces the native
                              gather fallback (ISSUE 17)
int4 quant matmul             TPU backend + single shard (see
                              :func:`use_quant_matmul`)
============================  ==============================================
"""

from __future__ import annotations

import logging
from contextlib import contextmanager

import jax

_FORCE_COMPILED = False

log = logging.getLogger(__name__)


@contextmanager
def force_compiled_kernels():
    """Within this context, kernel wrappers emit real Mosaic kernels (no
    interpret fallback) even on non-TPU hosts. Only useful together with AOT
    lowering for a TPU target — actually EXECUTING the result on CPU fails."""
    global _FORCE_COMPILED
    prev = _FORCE_COMPILED
    _FORCE_COMPILED = True
    try:
        yield
    finally:
        _FORCE_COMPILED = prev


def kernel_interpret() -> bool:
    """Interpret-mode decision for every Pallas wrapper call site."""
    if _FORCE_COMPILED:
        return False
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# dispatch gates — one predicate per kernel
# ---------------------------------------------------------------------------


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def single_shard(spec) -> bool:
    """One model-parallel shard: the auto condition for kernels whose
    pallas_call carries no GSPMD partitioning rule — a sharded operand
    would be all-gathered per launch. The ragged mixed-step kernel is the
    exception: its dispatch shard_maps over the head axis instead."""
    return spec.model_parallel == 1


def flash_shape_ok(spec, seq_len: int) -> bool:
    # q/k tiles are (128, D): seq must tile evenly; D must be a lane-aligned
    # multiple of 64. D=64 models (Llama-3.2-1B class) normally ride the
    # head-pair PACKED kernel (two heads fill the 128 lanes, use_packed);
    # with packing off they fall back to half-lane tiles — slight waste,
    # but still kernel-eligible.
    return seq_len >= 128 and seq_len % 128 == 0 and spec.head_dim % 64 == 0


def use_flash(spec, seq_len: int) -> bool:
    """Prefill flash attention (modules/attention.attention_prefill)."""
    if spec.use_flash_kernel is False:
        return False
    ok = flash_shape_ok(spec, seq_len)
    if spec.use_flash_kernel:  # force-enabled still honors shape guards
        if not ok:
            log.warning(
                "attn_kernel_enabled=True but shape (seq=%d, head_dim=%d) is "
                "unsupported by the flash kernel; falling back to native path",
                seq_len,
                spec.head_dim,
            )
        return ok
    return ok and single_shard(spec) and on_tpu()


def use_packed(spec) -> bool:
    """Head-pair packing decision, taken AFTER :func:`use_flash` says yes
    (seq-length eligibility is already settled there).

    Auto-on for head_dim <= 64 (the packing exists exactly because D=64
    half-fills the 128-wide MXU contraction; D=128 tiles are already full).
    Needs >= 2 heads to pair (H odd pads inside the kernel wrapper, H=1
    would only add waste). Tri-state ``use_packed_heads`` overrides like the
    other kernel switches — force-enable still honors the shape guards."""
    if spec.use_packed_heads is False:
        return False
    ok = spec.head_dim <= 64 and spec.num_heads >= 2
    if spec.use_packed_heads and not ok:
        log.warning(
            "attn_packed_kernel_enabled=True but shape (heads=%d, "
            "head_dim=%d) is unsupported by the packed kernel; using the "
            "unpacked flash path",
            spec.num_heads,
            spec.head_dim,
        )
    return ok


def use_tkg(spec, q_len: int, kv_width: int) -> bool:
    """Gate for the decode kernels (contiguous + paged TKG).
    ``spec.use_tkg_kernel`` (config attn_block_tkg_kernel_enabled): None =
    auto on TPU, True = force (still honoring shape guards), False = native
    path."""
    enabled = spec.use_tkg_kernel
    if enabled is False:
        return False
    ok = (
        q_len <= 16
        and spec.head_dim % 64 == 0
        and kv_width >= 128
        and kv_width % min(512, kv_width) == 0
    )
    if enabled:
        return ok
    # auto path: single model-parallel shard only — pallas_call has no GSPMD
    # partitioning rule, so a head-sharded cache operand would be all-gathered
    # per layer per step (force-enable opts in regardless)
    return ok and kv_width >= 512 and single_shard(spec) and on_tpu()


def use_paged_flash(spec, q_len: int) -> bool:
    """Gate for the paged prefill kernel: multi-token block attention only
    (decode q_len==1 rides the TKG kernel), lane-aligned head_dim; auto-on
    for TPU at kernel-worthy chunk sizes, force-on/off via
    attn_kernel_enabled."""
    if spec.use_flash_kernel is False or q_len < 8 or spec.head_dim % 64 != 0:
        return False
    if spec.use_flash_kernel:
        return True
    # auto path requires one model-parallel shard (see AttnSpec.model_parallel)
    return q_len >= 64 and single_shard(spec) and on_tpu()


def use_moe_tkg(spec, params: dict, n_tokens: int) -> bool:
    """Gate for the fused MoE decode kernel (``spec`` is a MoESpec). Plain
    unquantized bias-free GLU experts, decode-sized token counts, single
    model-parallel shard. AUTO stays OFF pending hardware wins; force-enable
    still honors these structural guards but WARNS on fallback (the
    flash-kernel convention)."""
    enabled = spec.moe_fused_kernel
    if not enabled:  # None (auto) stays OFF pending broader hardware wins
        return False
    plain = all(
        isinstance(params.get(k), dict)
        and "weight" in params[k]
        and "scale" not in params[k]
        and "bias" not in params[k]
        for k in ("gate_proj", "up_proj", "down_proj")
    )
    ok = (
        plain
        and n_tokens * spec.top_k <= 64
        and spec.ep_degree == 1
        and single_shard(spec)
        and not spec.early_affinity_modulation
    )
    if not ok:
        log.warning(
            "moe_fused_kernel_enabled=True but this configuration is "
            "unsupported (needs plain unquantized bias-free experts, "
            "T*k <= 64, ep=1, model_parallel=1, no early affinity "
            "modulation); falling back to the dense all-experts path"
        )
    return ok


def use_ragged(spec, total_q: int, ragged_q_tile: int = 16) -> bool:
    """Kernel/native gate for the ragged mixed-step attention: lane-aligned
    head_dim and tile-aligned packing; tri-state force via
    ``use_flash_kernel`` like the other attention kernels.

    Unlike the other gates there is NO single-shard condition: the mixed
    step dispatches the kernel through ``shard_map`` over the head-parallel
    grid axis (q heads and paged KV blocks are head-sharded, descriptors
    are replicated host metadata), so tp>1 meshes run the kernel per-shard
    with no collectives inside (ISSUE 17). The head counts must divide the
    model-parallel degree — guaranteed by GQASharding's kv replication, and
    re-checked here so a hand-built spec degrades to the native path
    instead of a shard_map error."""
    if (
        spec.use_flash_kernel is False
        or spec.head_dim % 64 != 0
        or total_q % ragged_q_tile != 0
    ):
        return False
    mp = spec.model_parallel
    if mp > 1 and (spec.num_heads % mp or spec.num_kv_heads % mp):
        return False
    if spec.use_flash_kernel:
        return True
    return on_tpu()


# --- int4 quant matmul (ops/quant_matmul.py) -------------------------------
#
# The decode linears reach the kernel through ops/quant.linear(), which sees
# only the packed entry and the activations — no AttnSpec/config. The mode
# is therefore process-level module state, set once by the application at
# load time ("auto" unless tp>1 forces it off) and overridable in tests via
# the quant_matmul_mode context.

_QMM_MODE: list = ["auto"]  # stack: [base, *context overrides]

#: default scale-group size along the input axis (two nibble planes of
#: 2*QMM_GROUP codes per packed byte row — see ops/quant_matmul.py)
QMM_GROUP = 128


def set_quant_matmul_mode(mode) -> None:
    """Set the process-level base mode: "auto" | True | False. The
    application calls this at load for weight_dtype="int4" (False on tp>1
    meshes: pallas_call has no GSPMD rule, so sharded packed weights would
    be all-gathered per launch — the native int4 path is GSPMD-shardable
    and serves those meshes instead)."""
    if mode not in ("auto", True, False):
        raise ValueError(f"quant matmul mode must be 'auto'/True/False, got {mode!r}")
    _QMM_MODE[0] = mode


@contextmanager
def quant_matmul_mode(mode):
    """Temporarily override the quant-matmul dispatch mode (tests force the
    kernel on CPU hosts with ``quant_matmul_mode(True)`` — it then runs in
    interpret mode via :func:`kernel_interpret`)."""
    if mode not in ("auto", True, False):
        raise ValueError(f"quant matmul mode must be 'auto'/True/False, got {mode!r}")
    _QMM_MODE.append(mode)
    try:
        yield
    finally:
        _QMM_MODE.pop()


def use_quant_matmul(rows: int, k: int, n: int, group: int = QMM_GROUP) -> bool:
    """Gate for the int4 fused-dequant matmul kernel: decode-sized row
    counts (the kernel keeps the full row block resident), lane-aligned
    output width, at least one full double-group along the input axis.
    Force-enable (mode True) still honors the shape guards but warns on
    fallback, the convention every other gate follows."""
    mode = _QMM_MODE[-1]
    if mode is False:
        return False
    from neuronx_distributed_inference_tpu.parallel.mesh import (
        ALL_AXES,
        ambient_mesh,
    )

    mesh = ambient_mesh()
    sharded = mesh is not None and any(
        dict(mesh.shape).get(a, 1) > 1 for a in ALL_AXES
    )
    ok = rows <= 64 and n % 128 == 0 and k >= 2 * group and not sharded
    if mode is True:
        if not ok:
            log.warning(
                "quant matmul forced on but the call (rows=%d, k=%d, n=%d, "
                "group=%d, model-sharded mesh=%s) is unsupported by the "
                "kernel; using the native int4 dequant path",
                rows,
                k,
                n,
                group,
                sharded,
            )
        return ok
    return ok and on_tpu()
