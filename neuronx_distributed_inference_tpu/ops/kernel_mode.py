"""Pallas kernel execution-mode switch.

Off-TPU hosts run every Pallas kernel in interpret mode (pure-Python
emulation) so the CPU test mesh exercises kernel numerics. That also means no
CPU test can ever hit a **Mosaic lowering** error — the class of bug that
breaks only on hardware (r1 ``_pick_chunk``; r3 the flash ``key_valid``
BlockSpec). :func:`force_compiled_kernels` flips the wrappers to emit real
Mosaic kernels regardless of host backend, so the suite can AOT-lower every
kernel (and whole model programs) for the TPU target from a CPU host via
``jax.export(..., platforms=["tpu"])`` — see tests/test_tpu_lowering.py.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax

_FORCE_COMPILED = False


@contextmanager
def force_compiled_kernels():
    """Within this context, kernel wrappers emit real Mosaic kernels (no
    interpret fallback) even on non-TPU hosts. Only useful together with AOT
    lowering for a TPU target — actually EXECUTING the result on CPU fails."""
    global _FORCE_COMPILED
    prev = _FORCE_COMPILED
    _FORCE_COMPILED = True
    try:
        yield
    finally:
        _FORCE_COMPILED = prev


def kernel_interpret() -> bool:
    """Interpret-mode decision for every Pallas wrapper call site."""
    if _FORCE_COMPILED:
        return False
    return jax.default_backend() != "tpu"
