#!/usr/bin/env python
"""Benchmark driver entry: prints ONE JSON line with the headline metric.

Measures steady-state decode throughput (tokens/sec) on the available chip for
full-size random-weight models, mirroring the reference's benchmark_sampling
metric definitions (reference: utils/benchmark.py:479-499 —
throughput = runs·tokens·batch/total).

Points (VERDICT r3 #1/#3, r4 #1/#2/#3):
- llama-3.2-1B bf16: bs=1 decode (headline), TTFT, 512-token prefill, bs=4 decode
- llama-3.2-1B int8: bs=1 decode + TTFT (HBM-bound decode ⇒ int8 halves traffic)
- serving-under-load: 8 concurrent 1B int8 requests through ServingSession
  (chunked prefill + paged cache): aggregate decode tok/s + p50/p99 TTFT
- the SAME serving mix through the ragged mixed-step dispatch
  (serving_ragged=True, ISSUE 6): one ragged paged-attention dispatch per
  step instead of the CTE/TKG pair — the ragged_* summary keys (incl. the
  padded-token fraction) are the split-vs-ragged comparison
- llama-3.1-8B int8: bs=1 decode + TTFT (the closest single-chip proxy for the
  BASELINE.json 8B north star; int8 8B fits one 16G v5e chip)
- llama-3.2-1B bf16 16k long-context (VERDICT r5 weak #5): 16384-token
  prefill TTFT + decode at 16k context (~1 GB KV) — the budgeted, skippable
  last point that validates the retuned + head-packed prefill tiles where
  attention dominates

vs_baseline anchors against the reference's Llama3.2-1B-class integration
throughput gate (~1057 tok/s on 32 trainium cores,
test_llama3_2_1b_4layer_context_parallel.py:36-44). We run on ONE v5e chip,
so >1.0 means one TPU chip beats the 32-core trn gate.

Robustness contract (VERDICT r4 #1): the machine-readable summary line is
printed (stdout, flushed) IMMEDIATELY after the headline point and RE-printed,
updated, after every later point — so a driver-side kill anywhere mid-suite
still leaves a parseable last line. A total wall-clock budget
(``BENCH_BUDGET_S``, default 1200 s) skips not-yet-started points as
``skipped_budget`` and exits 0 so the suite finishes inside any sane driver
timeout instead of being killed by it.

Quantize-once (VERDICT r4 #2): quantized points persist a presharded int8
artifact under ``BENCH_CACHE_DIR`` (default ``.bench_cache/``, gitignored);
warm runs restore the sharded arrays directly — no host quantize walk, no
full-precision staging (reference quantize-at-prep posture,
application_base.py:744-797).

The whole measurement path (build → load → warmup → measure) is importable and
size-parameterized so the test suite smoke-runs the EXACT code path on CPU
(tests/test_bench_smoke.py) — two of three rounds shipped a bench-only crash
the suite missed (VERDICT r3 weak #2), and r4's artifact was voided by a
driver timeout the old all-or-nothing output format could not survive.
"""

import json
import os
import sys
import time
import warnings

# model shapes live in the device/cost model (the single source of truth the
# static roofline projections are computed from — ISSUE 11); bench rows and
# projections can therefore never disagree about the shape they describe
from neuronx_distributed_inference_tpu.analysis.device_model import (  # noqa: E402
    LLAMA_1B,
    LLAMA_1B_DRAFT4,
    LLAMA_8B,
)

TINY = dict(  # smoke-test model (CPU suite)
    model_type="llama",
    hidden_size=64,
    intermediate_size=128,
    num_attention_heads=4,
    num_key_value_heads=2,
    num_hidden_layers=2,
    vocab_size=128,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
    max_position_embeddings=256,
    hidden_act="silu",
    tie_word_embeddings=False,
)

# reference gates (BASELINE.md): 1B-class 32-core integration throughput, and
# the 8B bf16 trn1-32-core gate (1665 * 0.8)
BASELINE_1B = 1057.0
BASELINE_8B_GATE = 1332.0


def _budget_s() -> float:
    return float(os.environ.get("BENCH_BUDGET_S", "1200"))


def _cache_dir() -> str:
    return os.environ.get(
        "BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache"),
    )


def _wait_for_backend(max_wait_s=300):
    """The TPU lease is exclusive per-process and can take minutes to free."""
    import jax

    deadline = time.time() + max_wait_s
    while True:
        try:
            devs = jax.devices()
            return devs
        except RuntimeError as e:
            if time.time() > deadline:
                raise
            print(f"waiting for TPU backend: {e}", file=sys.stderr)
            time.sleep(15)
            # jax caches backend init failure; clear and retry
            try:
                from jax.extend.backend import clear_backends

                clear_backends()
            except Exception:
                pass


def build_app(
    hf_attrs,
    *,
    batch,
    seq_len,
    ce_buckets,
    tkg_buckets,
    dtype="bfloat16",
    quantized=False,
    cache_key=None,
    block_kv=False,
    extra_tpu=None,
    devices=None,
):
    """Build + load a random-weight app — the exact production code path.

    ``cache_key``: when set and ``quantized``, the final sharded params are
    persisted as a presharded artifact under BENCH_CACHE_DIR/<cache_key> and
    restored on later runs — quantize once, not per load (VERDICT r4 #2).
    """
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    def load_cfg(c):
        for k, v in hf_attrs.items():
            setattr(c, k, v)

    # persistent XLA compilation cache: bench points re-run across processes
    # and rounds; compiles (up to ~8 min for int8 8B) must be paid once
    try:
        from jax.experimental.compilation_cache import compilation_cache

        compilation_cache.set_cache_dir(os.path.join(_cache_dir(), "xla"))
    except Exception:
        pass
    kw = {}
    if block_kv:
        from neuronx_distributed_inference_tpu.config import ChunkedPrefillConfig

        kw = dict(
            is_continuous_batching=True,
            ctx_batch_size=1,
            is_block_kv_layout=True,
            pa_num_blocks=block_kv["num_blocks"],
            pa_block_size=block_kv["block_size"],
            is_chunked_prefill=True,
            chunked_prefill_config=ChunkedPrefillConfig(
                max_num_seqs=block_kv["max_seqs"],
                kernel_q_tile_size=block_kv.get("q_tile", 128),
            ),
        )
    tc = TpuConfig(
        batch_size=batch,
        seq_len=seq_len,
        dtype=dtype,
        enable_bucketing=True,
        context_encoding_buckets=list(ce_buckets),
        token_generation_buckets=list(tkg_buckets),
        quantized=quantized,
        # fused decode-layer kernels need the fused QKV weight layout; with it
        # they auto-enable on TPU (quantized configs fall back structurally)
        fused_qkv=not quantized,
        **kw,
        **(extra_tpu or {}),
    )
    mesh = None
    if devices is not None:
        # multi-replica router point: each replica's mesh over its own
        # device partition (on a 1-chip host the replicas share the chip —
        # correct but serialized; scale-out needs chips)
        from neuronx_distributed_inference_tpu.parallel.mesh import (
            mesh_from_config,
        )

        mesh = mesh_from_config(tc, devices=devices)
    app = TpuModelForCausalLM(
        None, LlamaInferenceConfig(tc, load_config=load_cfg), mesh=mesh
    )
    artifact = None
    if cache_key:
        artifact = os.path.join(_cache_dir(), cache_key)
    loaded = False
    if artifact and os.path.exists(os.path.join(artifact, "manifest.pkl")):
        from neuronx_distributed_inference_tpu.utils.presharded import (
            config_fingerprint,
            load_presharded,
        )

        t0 = time.time()
        try:
            restored = load_presharded(
                artifact, app.mesh, fingerprint=config_fingerprint(app.config)
            )
        except Exception as e:
            # corrupt/stale artifact (killed mid-write, recipe change):
            # degrade to a cold load + rewrite rather than failing the point
            print(f"presharded cache unusable ({e}); cold load", file=sys.stderr)
            import shutil

            shutil.rmtree(artifact, ignore_errors=True)
            restored = None
        if restored is not None:
            app.params, app._pspecs = restored
            app.init_kv_cache()
            loaded = True
            print(
                f"presharded cache hit {artifact} ({time.time() - t0:.1f}s)",
                file=sys.stderr,
            )
    if not loaded:
        t0 = time.time()
        app.load(random_weights=True)
        print(f"load (cold) {time.time() - t0:.1f}s", file=sys.stderr)
        if artifact:
            from neuronx_distributed_inference_tpu.utils.presharded import (
                config_fingerprint,
                save_presharded,
            )

            t0 = time.time()
            save_presharded(
                app.params, app._pspecs, artifact,
                fingerprint=config_fingerprint(app.config),
            )
            print(
                f"presharded cache write {artifact} ({time.time() - t0:.1f}s)",
                file=sys.stderr,
            )
    return app


def measure_point(app, *, batch, prompt_len, gen_len, long_prompt=None):
    """Warmup-compile then measure TTFT / decode throughput (+ optional
    long-prompt prefill throughput). Returns a dict of metrics including
    ``kv_bytes``, the cache's true HBM cost (codes + scales for quantized
    caches) — the quantity the kv-quant rows halve."""
    import numpy as np

    from neuronx_distributed_inference_tpu.modules.kvcache import cache_nbytes

    rng = np.random.RandomState(0)
    vocab = app.config.vocab_size - 10
    ids = rng.randint(0, vocab, size=(batch, prompt_len))
    mask = np.ones_like(ids)

    # warmup / compile — run the SAME programs the measured runs use
    # (gen_len-sized decode chunk and the 1-token TTFT path)
    t0 = time.time()
    app.generate(ids, mask, max_new_tokens=gen_len)
    app.generate(ids, mask, max_new_tokens=1)
    compile_s = time.time() - t0

    t0 = time.time()
    app.generate(ids, mask, max_new_tokens=1)
    ttft_ms = (time.time() - t0) * 1e3

    t0 = time.time()
    out = app.generate(ids, mask, max_new_tokens=gen_len)
    decode_tok_s = out.num_generated * batch / (time.time() - t0)

    res = {
        "ttft_ms": round(ttft_ms, 1),
        "decode_tok_s": round(decode_tok_s, 2),
        "compile_s": round(compile_s, 1),
        "kv_bytes": cache_nbytes(app.kv_cache),
    }
    if long_prompt:
        ids_l = rng.randint(0, vocab, size=(batch, long_prompt))
        mask_l = np.ones_like(ids_l)
        app.generate(ids_l, mask_l, max_new_tokens=1)  # compile
        t0 = time.time()
        app.generate(ids_l, mask_l, max_new_tokens=1)
        res["prefill_tok_s"] = round(long_prompt / (time.time() - t0), 1)
    return res


def _counter_delta(snap, base_snap, name, exclude_reasons=()):
    """Per-run counter delta between two registry snapshots (the PR-7
    containment-census convention). ``exclude_reasons`` drops samples whose
    ``reason`` label matches — the clean-traffic 0/0/0 pin excludes
    ``reason=backlog`` from the rejected count, because an open-loop
    goodput run INTENDS backlog refusals (ISSUE 14 satellite): they are
    workload pressure, not containment events, and they are reported under
    their own ``backlog_*`` keys."""

    def total(s):
        fam = s.get(name)
        if not fam:
            return 0
        return int(sum(
            smp["value"]
            for smp in fam["samples"]
            if smp.get("labels", {}).get("reason") not in exclude_reasons
        ))

    return total(snap) - total(base_snap)


def measure_serving(app, *, n_requests, prompt_len, gen_len):
    """Serving-under-load: concurrent requests with staggered arrivals through
    ServingSession (continuous batching + chunked prefill + paged cache).
    Aggregate decode throughput + per-request TTFT/ITL — the product metric
    for a serving framework (VERDICT r4 #3; reference serving hot path
    model_wrapper.py:582-751, async_execution.py:190).

    TTFT/ITL come from the runtime telemetry layer's per-request traces
    (telemetry/tracing.py) — the same instrumentation production serving
    exposes — not from bench-local stopwatch bookkeeping; the session's
    registry rides the process-default registry so ``--metrics-out`` dumps
    the full serving metric set for this point."""
    import numpy as np

    from neuronx_distributed_inference_tpu.runtime.serving import ServingSession
    from neuronx_distributed_inference_tpu.telemetry import (
        TelemetrySession,
        default_registry,
    )

    rng = np.random.RandomState(0)
    vocab = app.config.vocab_size - 10
    prompts = [
        rng.randint(0, vocab, size=(prompt_len,)).tolist() for _ in range(n_requests)
    ]

    def run_once(registry=None):
        # registry=None -> the session's own throwaway registry: the warmup
        # pass compiles every (q, kv) chunk program and its compile-dominated
        # TTFT/ITL observations must not pollute the --metrics-out dump
        app.init_kv_cache()  # fresh block pool between runs
        with TelemetrySession(registry=registry) as tel:
            session = ServingSession(app, telemetry=tel)
            produced = set()
            t_start = time.time()
            # staggered arrivals: 2 up-front, then one more every scheduler
            # step until all n_requests have arrived — prefill chunks
            # interleave with live decode (the continuous-batching regime,
            # not a static batch)
            next_idx = 0
            for _ in range(2):
                session.add_request(str(next_idx), prompts[next_idx],
                                    max_new_tokens=gen_len)
                next_idx += 1
            while True:
                produced.update(session.step())
                if next_idx < n_requests and session.free_slots:
                    session.add_request(str(next_idx), prompts[next_idx],
                                        max_new_tokens=gen_len)
                    next_idx += 1
                if next_idx >= n_requests:
                    if not session.active:
                        break
                    if len(produced) >= n_requests:
                        # every request admitted + producing: drain the decode
                        # tail in multi-step chunks (one host sync per chunk —
                        # vLLM-style multi-step scheduling; per-step scheduling
                        # through a TUNNELED chip is pure host-RTT)
                        session.run_to_completion(decode_chunk_size=16)
                        break
            total_s = time.time() - t_start
            counts = {rid: len(r.generated) for rid, r in session.requests.items()}
        return tel, counts, total_s

    run_once()  # warmup / compile pass over all (q, kv) chunk programs
    base_snap = default_registry().snapshot()  # census delta baseline
    tel, counts, total_s = run_once(default_registry())
    ttfts = [t * 1e3 for t in tel.ttft_values_s()]
    itls = [t * 1e3 for t in tel.itl_values_s()]
    total_tokens = sum(counts.values())

    def pct(vals, p):
        # one percentile implementation: the telemetry session's
        v = tel.percentile(vals, p / 100)
        return round(v, 1) if v is not None else None

    res = {
        "decode_tok_s": round(total_tokens / total_s, 2),
        "ttft_ms": pct(ttfts, 50),
        "ttft_p99_ms": pct(ttfts, 99),
        "itl_ms": pct(itls, 50),
        "itl_p99_ms": pct(itls, 99),
        "n_requests": n_requests,
        "total_tokens": total_tokens,
    }
    # fault-containment census (ISSUE 7): rejected/quarantined/preempted
    # counts sourced from the telemetry registry — on clean traffic all
    # three MUST be 0 (the containment layer's overhead proof; the first
    # hardware session compares these rows against pre-containment numbers).
    # The registry is the PROCESS-default (shared across bench points), so
    # each point reports the delta over its own measured run, not the
    # cumulative process totals.
    snap = tel.registry.snapshot()

    res["rejected"] = _counter_delta(
        snap, base_snap, "nxdi_requests_rejected_total",
        exclude_reasons=("backlog",),
    )
    res["quarantined"] = _counter_delta(
        snap, base_snap, "nxdi_rows_quarantined_total")
    res["preempted"] = _counter_delta(
        snap, base_snap, "nxdi_requests_preempted_total")
    # ragged mixed-step dispatch (serving_ragged): padded-token fraction of
    # the packed total-token buckets, from the mixed-step composition
    # histogram the session records per dispatch
    # host-gap telemetry (ISSUE 8): host-time fraction of serving step wall
    # time over THIS measured run — ~1.0 means the host loop, not the chip,
    # bounds throughput; the async-pipelined row should push it (and
    # absolute host ms/step) down vs the synchronous row. Computed as a
    # per-run DELTA over the step-timing histograms (like the containment
    # counters above), NOT from the process-shared cumulative gauge — the
    # registry spans bench points, and a row that never recorded step
    # timing must not inherit another row's value (or a default 0.0).
    def _hist_delta(name):
        def sc(s):
            fam = s.get(name)
            if not fam or not fam.get("samples"):
                return 0.0, 0
            smp = fam["samples"][0]
            return float(smp["sum"]), int(smp["count"])

        s1, c1 = sc(snap)
        s0, c0 = sc(base_snap)
        return s1 - s0, c1 - c0

    host_ms, host_n = _hist_delta("nxdi_step_host_ms")
    wait_ms, _ = _hist_delta("nxdi_step_fetch_wait_ms")
    if host_n > 0 and host_ms + wait_ms > 0:
        res["host_frac"] = round(host_ms / (host_ms + wait_ms), 4)
    mixed = snap.get("nxdi_mixed_step_rows")
    if mixed:
        base_mixed = base_snap.get("nxdi_mixed_step_rows")
        base_sums = (
            {s["labels"]["kind"]: s["sum"] for s in base_mixed["samples"]}
            if base_mixed
            else {}
        )
        sums = {
            s["labels"]["kind"]: s["sum"] - base_sums.get(s["labels"]["kind"], 0)
            for s in mixed["samples"]
        }
        denom = sums.get("padded_slots", 0) + sums.get("query_tokens", 0)
        if denom:
            res["padded_token_frac"] = round(
                sums.get("padded_slots", 0) / denom, 4
            )
    return res


def measure_serving_spec(target, draft, *, n_requests, prompt_len, gen_len, k):
    """Spec-ragged serving (ISSUE 12): the SAME staggered mix through
    SpeculativeServingSession with verification packed into the ragged
    mixed dispatch (serving_spec_ragged) — prefill chunks + decode rows +
    spec-verify rows in ONE program launch per step, draft proposals and
    the accepted-token frontier chained device-side, draft length adaptive
    per request. Beside the usual serving metrics the row reports
    ``spec_acceptance``: the measured per-draft acceptance RATE
    ((committed - rounds) / drafted, from the registry's acceptance and
    draft-length histograms) — the parameter the acceptance-parameterized
    projection is re-evaluated at, so the recorded ceiling tracks the
    workload the row actually saw (random weights ⇒ near-zero acceptance:
    this row's CPU/clean-bench number is the WORST-case overhead bound;
    spec-friendly acceptance comes from real checkpoints)."""
    import numpy as np

    from neuronx_distributed_inference_tpu.runtime.serving import (
        SpeculativeServingSession,
    )
    from neuronx_distributed_inference_tpu.telemetry import (
        TelemetrySession,
        default_registry,
    )

    rng = np.random.RandomState(0)
    vocab = target.config.vocab_size - 10
    prompts = [
        rng.randint(0, vocab, size=(prompt_len,)).tolist() for _ in range(n_requests)
    ]

    def run_once(registry=None):
        target.init_kv_cache()
        draft.init_kv_cache()
        with TelemetrySession(registry=registry) as tel:
            session = SpeculativeServingSession(
                target, draft, speculation_length=k, telemetry=tel
            )
            t_start = time.time()
            next_idx = 0
            for _ in range(2):
                session.add_request(str(next_idx), prompts[next_idx],
                                    max_new_tokens=gen_len)
                next_idx += 1
            while True:
                session.step()
                if next_idx < n_requests and session.free_slots:
                    session.add_request(str(next_idx), prompts[next_idx],
                                        max_new_tokens=gen_len)
                    next_idx += 1
                    continue
                if next_idx >= n_requests and not (
                    session.active or session._readmit
                ):
                    break
            total_s = time.time() - t_start
            counts = {rid: len(r.generated) for rid, r in session.requests.items()}
        return tel, counts, total_s

    run_once()  # warmup / compile pass (mixed_spec buckets + chain programs)
    base_snap = default_registry().snapshot()
    tel, counts, total_s = run_once(default_registry())
    ttfts = [t * 1e3 for t in tel.ttft_values_s()]
    itls = [t * 1e3 for t in tel.itl_values_s()]
    total_tokens = sum(counts.values())

    def pct(vals, p):
        v = tel.percentile(vals, p / 100)
        return round(v, 1) if v is not None else None

    snap = tel.registry.snapshot()

    def _hist(which, name):
        fam = which.get(name)
        if not fam or not fam.get("samples"):
            return 0.0, 0
        smp = fam["samples"][0]
        return float(smp["sum"]), int(smp["count"])

    acc_s1, acc_c1 = _hist(snap, "nxdi_spec_accept_len")
    acc_s0, acc_c0 = _hist(base_snap, "nxdi_spec_accept_len")
    dl_s1, _ = _hist(snap, "nxdi_spec_draft_len")
    dl_s0, _ = _hist(base_snap, "nxdi_spec_draft_len")
    committed, rounds = acc_s1 - acc_s0, acc_c1 - acc_c0
    drafted = dl_s1 - dl_s0
    acceptance = (
        round(max(0.0, committed - rounds) / drafted, 4) if drafted > 0 else None
    )
    res = {
        "decode_tok_s": round(total_tokens / total_s, 2),
        "ttft_ms": pct(ttfts, 50),
        "ttft_p99_ms": pct(ttfts, 99),
        "itl_ms": pct(itls, 50),
        "itl_p99_ms": pct(itls, 99),
        "n_requests": n_requests,
        "total_tokens": total_tokens,
        "spec_acceptance": acceptance,
        "spec_rounds": int(rounds),
    }

    res["rejected"] = _counter_delta(
        snap, base_snap, "nxdi_requests_rejected_total",
        exclude_reasons=("backlog",),
    )
    res["quarantined"] = _counter_delta(
        snap, base_snap, "nxdi_rows_quarantined_total")
    res["preempted"] = _counter_delta(
        snap, base_snap, "nxdi_requests_preempted_total")
    return res


def measure_router(apps, *, n_requests, prompt_len, gen_len, policy,
                   prefill_apps=None, elastic=None):
    """Scale-out serving: the SAME staggered request mix routed over N
    single-chip replica sessions by ServingRouter (ISSUE 10;
    docs/SERVING.md "Multi-replica front-end"). Aggregate tok/s across
    replicas plus the router's own product metrics: failover count (MUST be
    0 on clean traffic — the router layer's zero-overhead proof) and
    ``balance_frac`` = min-replica tokens / even share (1.0 == the
    placement policy spread the mix perfectly).

    ``prefill_apps`` (ISSUE 15): prefill-stage apps forming a disaggregated
    PREFILL tier — every placement context-encodes there and hands KV over
    to a decode replica. The row then additionally reports the hand-off
    census: ``handoffs`` (MUST equal the request count on clean traffic),
    ``handoff_failures`` and ``handoff_local_prefill`` (both MUST be 0 —
    the tier's zero-containment-events proof).

    ``elastic`` (ISSUE 20): ``dict(retire_step=N)`` exercises the elastic
    fleet primitives mid-drain — at step N the highest-id replica is
    retired (``retire_replica``, graceful drain), and the moment its drain
    finalizes a FRESH session over the same warmed app re-joins via
    ``add_replica`` (zero recompiles: the jit cache is per-app). The row
    then reports the ``elastic_*`` census: retire/add counts, attainment
    (finished / submitted — MUST be 1.0), and the leak pins (zero leaked
    KV blocks across every session incl. the retired one, zero leaked
    threads across the run).

    Containment census matches PR 7's convention: rejected / failover /
    re-admitted are PER-RUN deltas against a pre-run registry snapshot."""
    import numpy as np

    from neuronx_distributed_inference_tpu.runtime.replica import (
        PrefillReplicaHandle,
    )
    from neuronx_distributed_inference_tpu.runtime.router import ServingRouter
    from neuronx_distributed_inference_tpu.runtime.serving import ServingSession
    from neuronx_distributed_inference_tpu.telemetry import (
        TelemetrySession,
        default_registry,
    )

    rng = np.random.RandomState(0)
    vocab = apps[0].config.vocab_size - 10
    prompts = [
        rng.randint(0, vocab, size=(prompt_len,)).tolist() for _ in range(n_requests)
    ]

    def run_once(registry=None):
        import threading as _threading

        threads_before = _threading.active_count()
        for app in apps:
            app.init_kv_cache()  # fresh block pool per replica between runs
        tier = []
        for i, papp in enumerate(prefill_apps or ()):
            papp.init_kv_cache()
            tier.append(PrefillReplicaHandle(papp, i))
        sessions = []
        elastic_info = None
        with TelemetrySession(registry=registry) as tel:
            # threaded stepping follows TpuConfig.router_threading on the
            # replica apps (the *_router_threaded row sets it); the context
            # manager joins the worker pool even if the drain raises
            # (no-op when sequential)
            with ServingRouter(
                [ServingSession(app, telemetry=tel) for app in apps],
                policy=policy, telemetry=tel, prefill_replicas=tier,
            ) as router:
                sessions = [h.session for h in router.replicas]
                retire_step = (elastic or {}).get("retire_step")
                retired_id = None
                added = False
                step_i = 0
                t_start = time.time()
                next_idx = 0
                for _ in range(2):
                    router.add_request(str(next_idx), prompts[next_idx],
                                       max_new_tokens=gen_len)
                    next_idx += 1
                while True:
                    router.step()
                    step_i += 1
                    if retire_step is not None:
                        if retired_id is None and step_i >= retire_step:
                            victim = max(
                                router.replicas, key=lambda h: h.replica_id
                            )
                            retired_id = victim.replica_id
                            router.retire_replica(retired_id, drain=True)
                        elif retired_id is not None and not added and all(
                            h.replica_id != retired_id
                            for h in router.replicas
                        ):
                            # drain finalized: re-join a FRESH session over
                            # the same warmed app (shared jit cache — zero
                            # recompiles)
                            sess = ServingSession(apps[-1], telemetry=tel)
                            sessions.append(sess)
                            router.add_replica(sess)
                            added = True
                    if next_idx < n_requests:
                        router.add_request(str(next_idx), prompts[next_idx],
                                           max_new_tokens=gen_len)
                        next_idx += 1
                        continue
                    if not router.has_live_work:
                        break
                total_s = time.time() - t_start
                counts = {
                    rid: len(r.tokens)
                    for rid, r in router.requests.items()
                }
                per_replica = [h.tokens_served for h in router.replicas]
                threaded = router.threaded
                handoffs = sum(p.handoffs for p in router.prefill_replicas)
                if retire_step is not None:
                    elastic_info = {
                        "elastic_retired": int(retired_id is not None),
                        "elastic_added": int(added),
                        "elastic_attainment": round(
                            sum(
                                1 for r in router.requests.values()
                                if r.status == "finished"
                            ) / n_requests, 4,
                        ),
                        # every session's allocator drained (the retired
                        # one included): nothing a retired replica owned
                        # leaks a KV block
                        "elastic_leaked_blocks": sum(
                            len(getattr(s.allocator, "seq_blocks", ()) or ())
                            for s in sessions
                        ),
                    }
        if elastic_info is not None:
            elastic_info["elastic_leaked_threads"] = (
                _threading.active_count() - threads_before
            )
        return (tel, counts, per_replica, total_s, threaded, handoffs,
                elastic_info)

    run_once()  # warmup / compile pass over every replica's programs
    base_snap = default_registry().snapshot()
    (tel, counts, per_replica, total_s, threaded, handoffs,
     elastic_info) = run_once(default_registry())
    total_tokens = sum(counts.values())
    snap = tel.registry.snapshot()

    def _ctr(name, exclude_reasons=()):
        return _counter_delta(snap, base_snap, name,
                              exclude_reasons=exclude_reasons)

    def _hist_sum(name):
        def total(s):
            fam = s.get(name)
            if not fam:
                return 0.0
            return float(sum(smp["sum"] for smp in fam["samples"]))

        return total(snap) - total(base_snap)

    # per-step overlap (ISSUE 13): 1 - stepping-phase wall / sum of the
    # per-replica step walls, per-run deltas over the nxdi_replica_step_ms
    # histograms + the router-step span — ~0 when replicas host-serialize
    # (sequential stepping), up to (N-1)/N when the thread-per-replica
    # pool overlaps them fully
    replica_ms = _hist_sum("nxdi_replica_step_ms")
    phase_ms = _hist_sum("nxdi_router_step_ms")
    overlap = (
        round(max(0.0, 1.0 - phase_ms / replica_ms), 4)
        if replica_ms > 0 else None
    )

    n = len(apps)
    even_share = total_tokens / n if n else 0
    res = {
        "decode_tok_s": round(total_tokens / total_s, 2),
        "n_requests": n_requests,
        "n_replicas": n,
        "total_tokens": total_tokens,
        "tokens_per_replica": per_replica,
        "balance_frac": (
            round(min(per_replica) / even_share, 4) if even_share else None
        ),
        "router_threading": threaded,
        "overlap_frac": overlap,
        # containment deltas (PR 7 convention): clean traffic MUST report
        # 0 failovers — the pre-flip check for any failover-policy knob
        "rejected": _ctr("nxdi_router_rejected_total")
        + _ctr("nxdi_requests_rejected_total", exclude_reasons=("backlog",)),
        "failover": _ctr("nxdi_router_failovers_total"),
        # re-admissions = pool-exhaustion evictions that re-queued inside a
        # replica (aging); also exposed under PR 7's "preempted" name so
        # every serving row carries the same containment key set
        "readmitted": _ctr("nxdi_requests_preempted_total"),
        "preempted": _ctr("nxdi_requests_preempted_total"),
        "quarantined": _ctr("nxdi_rows_quarantined_total"),
    }
    if prefill_apps:
        # disaggregated-tier census (ISSUE 15): on clean traffic every
        # prompt hands off (handoffs == n_requests) with ZERO typed
        # hand-off failures and ZERO local-prefill fallbacks
        res["n_prefill_replicas"] = len(prefill_apps)
        res["handoffs"] = handoffs
        res["handoff_failures"] = _ctr("nxdi_handoff_failures_total")
        res["handoff_local_prefill"] = _ctr("nxdi_handoff_local_prefill_total")
        res["handoff_retries"] = _ctr("nxdi_handoff_retries_total")
    if elastic_info is not None:
        # elastic-fleet census (ISSUE 20): retire + add both happened,
        # every submitted request finished (attainment 1.0) and nothing
        # leaked — blocks or threads
        res.update(elastic_info)
        res["elastic_events"] = _ctr("nxdi_router_elastic_total")
    return res


def measure_goodput(apps, *, workload, chaos_kill_step=None,
                    policy="least_loaded", bucket_steps=4,
                    prefill_apps=None, chaos_tier="decode"):
    """Open-loop SLO goodput (ISSUE 14; docs/WORKLOADS.md): a seeded
    workload trace (arrival process × heavy-tailed lengths × shared-prefix
    tenant pools) drives the serving stack through the open-loop
    WorkloadDriver on a VIRTUAL clock — requests are admitted no earlier
    than their arrival step, refused arrivals retry from the backlog, and
    every latency policy in the stack (deadlines, EWMAs, telemetry traces)
    runs on deterministic virtual time. The scored number is **goodput**:
    tokens from requests that met their TTFT/ITL SLOs (measured from
    ARRIVAL, so backlog wait counts) per wall second, beside the raw
    ``decode_tok_s`` the closed-loop rows report.

    ``apps``: one app = single ServingSession; N apps = a ServingRouter
    over N replica sessions. ``chaos_kill_step``: arm the standing chaos
    row — a seeded replica kill mid-run, scored as goodput-dip depth +
    recovery time off the time-bucketed goodput series (workload/slo.py
    extract_dip). ``prefill_apps`` (ISSUE 15): a disaggregated PREFILL
    tier in front of the decode replicas; ``chaos_tier="prefill"`` aims
    the kill at a tier member instead of a decode replica — decode
    capacity survives, so the scorer's recovery target stays at the FULL
    baseline (alive_frac 1.0) and the row's claim is containment (local-
    prefill fallback, no wedge), not a capacity dip. Containment deltas follow the PR-7 convention with
    ``reason=backlog`` EXCLUDED from the rejected count: open-loop backlog
    refusals are intended workload pressure, reported under
    ``backlog_refusals`` instead."""
    from neuronx_distributed_inference_tpu.runtime.replica import ReplicaHandle
    from neuronx_distributed_inference_tpu.runtime.router import ServingRouter
    from neuronx_distributed_inference_tpu.runtime.serving import ServingSession
    from neuronx_distributed_inference_tpu.telemetry import (
        SloMonitor,
        TelemetrySession,
        default_registry,
    )
    from neuronx_distributed_inference_tpu.workload import (
        ChaosPlan,
        VirtualClock,
        WorkloadDriver,
        generate,
        score,
        standard_spec,
    )

    from neuronx_distributed_inference_tpu.runtime.replica import (
        PrefillReplicaHandle,
    )

    trace = generate(standard_spec(
        vocab_size=apps[0].config.vocab_size - 10, **workload
    ))
    chaos = (
        ChaosPlan(kill_step=chaos_kill_step, tier=chaos_tier)
        if chaos_kill_step is not None else None
    )

    def run_once(registry=None):
        for app in apps:
            app.init_kv_cache()
        tier = []
        for i, papp in enumerate(prefill_apps or ()):
            papp.init_kv_cache()
            tier.append(PrefillReplicaHandle(papp, i))
        vc = VirtualClock()
        with TelemetrySession(registry=registry, clock=vc.now) as tel:
            # live windowed SLO attainment / burn rate rides every goodput
            # run (ISSUE 19) — the nxdi_slo_burn_rate gauges land in the
            # --metrics-out dump beside the offline scorer's numbers
            tel.attach_slo_monitor(SloMonitor())
            sessions = [
                ServingSession(app, telemetry=tel, clock=vc.now)
                for app in apps
            ]
            t_start = time.time()
            if len(apps) > 1:
                handles = [
                    ReplicaHandle(s, i, clock=vc.now)
                    for i, s in enumerate(sessions)
                ]
                with ServingRouter(handles, policy=policy, telemetry=tel,
                                   clock=vc.now,
                                   prefill_replicas=tier) as router:
                    drv = WorkloadDriver(router, trace, clock=vc,
                                         telemetry=tel, chaos=chaos)
                    with warnings.catch_warnings():
                        # a chaos prefill-tier kill degrades to local
                        # prefill LOUDLY (that one warning is the product
                        # behavior under test, not an error); anything else
                        # stays visible
                        warnings.filterwarnings(
                            "ignore",
                            message="disaggregated prefill tier is DEAD",
                        )
                        result = drv.run()
            else:
                drv = WorkloadDriver(sessions[0], trace, clock=vc,
                                     telemetry=tel)
                result = drv.run()
            total_s = time.time() - t_start
            report = score(result, tel, bucket_steps=bucket_steps)
            trace_out = _trace_out_path()
            if trace_out and registry is not None:
                # measured pass only (the warmup pass would overwrite the
                # real timeline with compile-dominated spans)
                tel.export_chrome_trace(trace_out)
                print(f"chrome trace -> {trace_out}", file=sys.stderr)
        return result, report, total_s

    run_once()  # warmup / compile pass over every program the trace touches
    base_snap = default_registry().snapshot()
    result, report, total_s = run_once(default_registry())
    snap = default_registry().snapshot()
    res = {
        "decode_tok_s": round(report.total_tokens / total_s, 2),
        "goodput_tok_s": round(report.slo_met_tokens / total_s, 2),
        "slo_attainment": report.attainment,
        "slo_attainment_by_tenant": report.attainment_by_tenant,
        "slo_misses": report.misses_by_kind,
        "slo_met_tokens": report.slo_met_tokens,
        "total_tokens": report.total_tokens,
        "n_requests": len(trace.arrivals),
        "n_replicas": len(apps),
        "virtual_steps": result.steps,
        "backlog_refusals": result.backlog_refusals,
        "goodput_series": report.series,
        "workload_digest": trace.digest(),
        # containment deltas (PR 7 convention), backlog EXCLUDED from
        # rejected — the open-loop rows intend backlog refusals
        "rejected": _counter_delta(
            snap, base_snap, "nxdi_requests_rejected_total",
            exclude_reasons=("backlog",),
        ) + _counter_delta(snap, base_snap, "nxdi_router_rejected_total"),
        "backlog_rejected": _counter_delta(
            snap, base_snap, "nxdi_requests_rejected_total",
        ) - _counter_delta(
            snap, base_snap, "nxdi_requests_rejected_total",
            exclude_reasons=("backlog",),
        ),
        "quarantined": _counter_delta(
            snap, base_snap, "nxdi_rows_quarantined_total"),
        "preempted": _counter_delta(
            snap, base_snap, "nxdi_requests_preempted_total"),
    }
    if prefill_apps:
        res["n_prefill_replicas"] = len(prefill_apps)
        res["handoff_failures"] = _counter_delta(
            snap, base_snap, "nxdi_handoff_failures_total")
        res["handoff_local_prefill"] = _counter_delta(
            snap, base_snap, "nxdi_handoff_local_prefill_total")
    if chaos is not None:
        res["chaos"] = result.chaos
        res["failover"] = _counter_delta(
            snap, base_snap, "nxdi_router_failovers_total")
        dip = report.dip
        res["goodput_dip_frac"] = dip.dip_frac if dip else None
        res["goodput_recovery_steps"] = (
            dip.recovery_steps if dip else None
        )
    return res


def _suite_params(tiny):
    if tiny:
        attrs_1b = attrs_8b = TINY
        prompt, gen, long_prompt = 16, 8, 32
        seq, ce, tkg = 64, [16, 32], [32, 64]
        ce4, tkg4 = [16], [32]
        serving = dict(n_requests=3, prompt=12, gen=6, seq=64,
                       blocks=24, block_size=16, max_seqs=4, q_tile=16)
        lc = dict(prompt=48, gen=8, seq=64, ce=[48], tkg=[64])
        mc = dict(prompt=32, gen=8, seq=64, ce=[32], tkg=[64])
        # open-loop goodput workloads (ISSUE 14): generous SLOs on the CPU
        # harness — the clean row must pin slo_attainment == 1.0; the burst
        # row's on/off arrivals overrun the 4 slots so backlog refusals
        # actually happen; the chaos row needs sustained decode so the
        # seeded replica kill lands mid-stream
        wl = dict(seed=14, n_requests=8, rate=1.5, arrival_kind="poisson",
                  shared_prefix_len=8, max_prompt_len=16,
                  min_output_len=4, max_output_len=8,
                  ttft_slo_s=1e4, itl_slo_s=1e3)
        wl_burst = dict(seed=14, n_requests=10, rate=4.0,
                        arrival_kind="onoff", shared_prefix_len=8,
                        max_prompt_len=16, min_output_len=4,
                        max_output_len=8, ttft_slo_s=1e4, itl_slo_s=1e3)
        wl_chaos = dict(seed=14, n_requests=14, rate=1.0,
                        arrival_kind="poisson", shared_prefix_len=8,
                        max_prompt_len=16, min_output_len=12,
                        max_output_len=16, ttft_slo_s=1e4, itl_slo_s=1e3)
        chaos_kill = 8
    else:
        attrs_1b, attrs_8b = LLAMA_1B, LLAMA_8B
        prompt, gen, long_prompt = 128, 256, 512
        seq, ce, tkg = 1024, [128, 512], [512, 1024]
        ce4, tkg4 = [128], [512]
        serving = dict(n_requests=8, prompt=128, gen=128, seq=1024,
                       blocks=512, block_size=32, max_seqs=8)
        # 16k long-context point (VERDICT r5 weak #5): 1B shape, ~1 GB KV
        # ((B+1)=2 cache rows x 16896 x 8 kv heads x 64 x k+v x 16 layers
        # x bf16) — validates the retuned + head-packed prefill tiles at the
        # length where attention dominates. The 8k point pairs with it so the
        # bf16 vs *_kvq8 rows isolate the KV DMA term at both depths.
        # TKG buckets are 512-ALIGNED (8704 = 17*512, 16896 = 33*512) so the
        # TKG decode kernel is shape-eligible (use_tkg_kernel requires
        # kv_width % 512 == 0 — the old 16448 bucket silently pinned the
        # native gather path for long-context decode).
        lc = dict(prompt=16384, gen=32, seq=16896, ce=[16384], tkg=[16896])
        mc = dict(prompt=8192, gen=32, seq=8704, ce=[8192], tkg=[8704])
        # open-loop goodput workloads (ISSUE 14): hardware-scale traces.
        # SLOs stay generous for the clean row's attainment==1.0 contract;
        # SLO-sweep exploration (tight TTFT under burst) is an operator
        # exercise over the same seeded traces (docs/WORKLOADS.md)
        wl = dict(seed=14, n_requests=24, rate=2.0, arrival_kind="poisson",
                  shared_prefix_len=32, max_prompt_len=128,
                  min_output_len=32, max_output_len=128,
                  ttft_slo_s=1e4, itl_slo_s=1e3)
        wl_burst = dict(seed=14, n_requests=32, rate=8.0,
                        arrival_kind="onoff", shared_prefix_len=32,
                        max_prompt_len=128, min_output_len=32,
                        max_output_len=128, ttft_slo_s=1e4, itl_slo_s=1e3)
        wl_chaos = dict(seed=14, n_requests=32, rate=2.0,
                        arrival_kind="poisson", shared_prefix_len=32,
                        max_prompt_len=128, min_output_len=64,
                        max_output_len=128, ttft_slo_s=1e4, itl_slo_s=1e3)
        chaos_kill = 16
    return {
        # ORDER = budget priority: the headline first (its number is the
        # contract), then cheap points, the serving point, and the expensive
        # 8B transfer-bound point last.
        "bf16_1b_bs1": dict(
            attrs=attrs_1b, batch=1, seq=seq, ce=ce, tkg=tkg,
            prompt=prompt, gen=gen, long_prompt=long_prompt, quantized=False,
            cache_key="bf16_1b" if not tiny else None,
        ),
        "bf16_1b_bs4": dict(
            attrs=attrs_1b, batch=4, seq=seq, ce=ce4, tkg=tkg4,
            prompt=prompt, gen=gen, long_prompt=None, quantized=False,
            cache_key="bf16_1b" if not tiny else None,
        ),
        "int8_1b_bs1": dict(
            attrs=attrs_1b, batch=1, seq=seq, ce=ce[:1], tkg=tkg[:1],
            prompt=prompt, gen=gen, long_prompt=None, quantized=True,
            cache_key="int8_1b" if not tiny else None,
        ),
        # shares the int8_1b presharded artifact: same model/dtype/recipe —
        # only the KV layout differs, which is not part of the artifact
        "serving_1b_int8": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            cache_key="int8_1b" if not tiny else None,
        ),
        # SAME request mix through the ragged mixed-step dispatch (ISSUE 6):
        # one ragged dispatch per step replaces the CTE/TKG pair — the pair
        # of rows is the split-vs-ragged serving comparison for the next
        # hardware session. Own artifact key: serving_ragged is part of the
        # config fingerprint, so sharing int8_1b's would thrash it.
        # serving_ragged_async pinned OFF here: this is the SYNCHRONOUS
        # ragged row the *_ragged_async row below is measured against.
        "serving_1b_int8_ragged": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            extra_tpu=dict(serving_ragged=True, serving_ragged_async=False),
            cache_key="int8_1b_ragged" if not tiny else None,
        ),
        # SAME ragged mix with grouped-int4 weights (ISSUE 17): the serving
        # side of the weight-streaming pair — decode slots stream packed
        # int4 projections while prefill rides the same ragged dispatch.
        # Beside serving_1b_int8_ragged this isolates the weight-bandwidth
        # term under a mixed CE+TKG serving load. Own artifact key:
        # weight_dtype is part of the config fingerprint.
        "serving_1b_int4_ragged": dict(
            attrs=attrs_1b, quantized=False, serving=serving,
            extra_tpu=dict(weight_dtype="int4", serving_ragged=True,
                           serving_ragged_async=False),
            cache_key="int4_1b_ragged" if not tiny else None,
        ),
        # SAME mix again with async 1-ahead pipelining on the ragged path
        # (ISSUE 8): step k+1 chains on step k's on-device tokens, the fetch
        # is non-blocking, host bookkeeping overlaps the device — the
        # ragged_async_* keys vs ragged_* quantify the overlap win and
        # serving_host_frac localizes what host gap remains.
        "serving_1b_int8_ragged_async": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            extra_tpu=dict(serving_ragged=True, serving_ragged_async=True),
            cache_key="int8_1b_ragged_async" if not tiny else None,
        ),
        # SAME mix with speculative verification packed INTO the ragged
        # mixed dispatch (ISSUE 12, serving_spec_ragged): one
        # mixed_step_spec launch per step serves prefill + decode +
        # spec-verify rows; draft proposals and the accepted-token frontier
        # chain device-side; draft length adapts per request. The 4-layer
        # 1B-width draft shape is shared with the acceptance-parameterized
        # projection (device_model.LLAMA_1B_DRAFT4). Own artifact key:
        # serving_spec_ragged + speculation_length are in the fingerprint.
        "serving_1b_int8_spec_ragged": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            spec=dict(
                speculation_length=4,
                draft_attrs=TINY if tiny else LLAMA_1B_DRAFT4,
                draft_cache_key="int8_1b_draft4" if not tiny else None,
            ),
            extra_tpu=dict(serving_ragged=True, serving_ragged_async=True,
                           serving_spec_ragged=True, speculation_length=4),
            cache_key="int8_1b_spec_ragged" if not tiny else None,
        ),
        # SAME mix routed over 2 single-chip replicas by ServingRouter
        # (ISSUE 10): the scale-out row. On a 1-chip host both replicas
        # share the chip (correct, serialized — the row then measures the
        # router layer's overhead); with 2+ chips each replica gets its own
        # device partition and router_tok_s is the data-parallel scale-out
        # number. Shares the int8_1b serving artifact (identical model
        # config; the router is a layer above the session).
        "serving_1b_int8_router": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            router=dict(replicas=2, policy="least_loaded",
                        n_requests=4 if tiny else 8),
            cache_key="int8_1b" if not tiny else None,
        ),
        # SAME routed mix with THREAD-PER-REPLICA stepping (ISSUE 13,
        # TpuConfig.router_threading): every alive replica's step()
        # dispatches from a persistent worker pool behind a per-step
        # barrier, so replica device steps overlap instead of
        # host-serializing. Beside the sequential router row this pair is
        # the threading win: router_threaded_tok_s vs router_tok_s, and
        # router_step_overlap_frac (from the nxdi_replica_step_ms
        # histograms + the router-step span) measures how much of the
        # per-replica step wall actually overlapped (0 = serialized,
        # 0.5 = two replicas fully concurrent). Own artifact key:
        # router_threading is part of the config fingerprint.
        "serving_1b_int8_router_threaded": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            router=dict(replicas=2, policy="least_loaded",
                        n_requests=4 if tiny else 8),
            extra_tpu=dict(router_threading=True),
            cache_key="int8_1b_router_threaded" if not tiny else None,
        ),
        # SAME routed mix with a DISAGGREGATED PREFILL TIER (ISSUE 15,
        # TpuConfig.router_prefill_replicas): one dedicated prefill replica
        # context-encodes every prompt and hands the populated KV over to
        # the 2 decode replicas — no decode replica ever runs a prefill, so
        # long-prompt bursts cannot stall co-located decode ITL. The
        # hand-off needs the CONTIGUOUS cache (whole-line scatter), so this
        # row runs the contiguous serving config; its containment deltas
        # must be 0/0/0 on clean traffic AND handoffs == requests with
        # ZERO hand-off failures / local-prefill fallbacks (the tier's
        # zero-containment-events proof). Own artifact keys: the stage
        # split is part of the config fingerprint.
        "serving_1b_int8_disagg": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            router=dict(replicas=2, policy="least_loaded",
                        n_requests=4 if tiny else 8),
            disagg=dict(prefill_replicas=1),
            cache_key="int8_1b_disagg" if not tiny else None,
        ),
        # SAME routed mix under an ELASTIC fleet (ISSUE 20): at a seeded
        # step mid-drain one replica is RETIRED (placement stops, its owned
        # requests drain in place, worker joined on finalize) and a fresh
        # session over the same warmed app re-joins via add_replica (the
        # jit cache is per-app — zero recompiles). The elastic_* census
        # pins attainment == 1.0 with ZERO leaked KV blocks/threads — the
        # scale-in/scale-out path is free under clean traffic, exactly
        # what the lifecycle audit (LIFE801/804/805) licenses statically.
        # Shares the int8_1b serving artifact (identical model config; the
        # elastic machinery is router bookkeeping above the session).
        "serving_1b_int8_elastic": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            router=dict(replicas=2, policy="least_loaded",
                        n_requests=4 if tiny else 8),
            elastic=dict(retire_step=2),
            cache_key="int8_1b" if not tiny else None,
        ),
        # Open-loop SLO goodput rows (ISSUE 14, docs/WORKLOADS.md): a seeded
        # workload trace (Poisson / bursty arrivals, heavy-tailed lengths,
        # shared-prefix tenants) drives the SAME serving config through the
        # WorkloadDriver on a virtual clock, scored as goodput-under-SLO
        # (tokens from TTFT/ITL-met requests) instead of drain tok/s. The
        # clean row pins slo_attainment == 1.0 under generous SLOs; the
        # burst row's on/off arrival bursts overrun the slot count, so the
        # driver backlog (and its refusal census) actually engages; the
        # chaos row routes over 2 replicas and kills one mid-run (seeded),
        # scored as goodput-dip depth + recovery time off the time-bucketed
        # goodput series. Shares the int8_1b serving artifact (identical
        # model config — the workload layer sits above the session).
        "serving_1b_int8_goodput": dict(
            attrs=attrs_1b, quantized=True, serving=serving, workload=wl,
            cache_key="int8_1b" if not tiny else None,
        ),
        "serving_1b_int8_goodput_burst": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            workload=wl_burst,
            cache_key="int8_1b" if not tiny else None,
        ),
        "serving_1b_int8_goodput_chaos": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            workload=wl_chaos,
            chaos=dict(replicas=2, kill_step=chaos_kill),
            cache_key="int8_1b" if not tiny else None,
        ),
        # the standing DISAGGREGATED chaos row (ISSUE 15): the same seeded
        # open-loop trace over 2 decode replicas + 1 prefill replica, with
        # the chaos kill aimed at the PREFILL TIER mid-run. Decode capacity
        # survives — placements degrade to local monolithic prefill (the
        # loud nxdi_handoff_local_prefill_total census) — so the pinned
        # claim is containment: attainment holds, goodput recovers finitely
        # against the FULL baseline (alive_frac 1.0), nothing wedges.
        "serving_1b_int8_disagg_chaos": dict(
            attrs=attrs_1b, quantized=True, serving=serving,
            workload=wl_chaos,
            chaos=dict(replicas=2, kill_step=chaos_kill, tier="prefill"),
            disagg=dict(prefill_replicas=1),
            cache_key="int8_1b_disagg" if not tiny else None,
        ),
        # single-chip proxy for the BASELINE 8B north star: int8 8B fits 16G
        "int8_8b_bs1": dict(
            attrs=attrs_8b, batch=1, seq=seq, ce=ce[:1], tkg=tkg[:1],
            prompt=prompt, gen=gen, long_prompt=None, quantized=True,
            cache_key="int8_8b" if not tiny else None,
        ),
        # int4 weight-streaming flagship (ISSUE 17): the SAME 8B shape with
        # grouped-int4 packed weights (weight_dtype="int4") — decode streams
        # ~0.53 byte/param (codes + group scales) through the fused-dequant
        # quant_matmul kernel, vs int8's 1 byte. Beside int8_8b_bs1 this
        # pair is the weight-bandwidth halving measured where decode is
        # weight-bound. Own artifact key: weight_dtype joins the config
        # fingerprint, so sharing int8_8b's would thrash it.
        "bf16_8b_int4": dict(
            attrs=attrs_8b, batch=1, seq=seq, ce=ce[:1], tkg=tkg[:1],
            prompt=prompt, gen=gen, long_prompt=None, quantized=False,
            extra_tpu=dict(weight_dtype="int4"),
            cache_key="bf16_8b_int4" if not tiny else None,
        ),
        # LAST in budget priority: the expensive long-context points are the
        # first casualties of a tight BENCH_BUDGET_S (skippable by design).
        # The 8k/16k bf16 vs *_kvq8 pairs report kv_bytes + decode tok/s so
        # the KV-quant bandwidth win is measured where KV DMA dominates.
        "bf16_1b_8k": dict(
            attrs=attrs_1b, batch=1, seq=mc["seq"], ce=mc["ce"],
            tkg=mc["tkg"], prompt=mc["prompt"], gen=mc["gen"],
            long_prompt=None, quantized=False,
            cache_key="bf16_1b" if not tiny else None,
        ),
        "bf16_1b_8k_kvq8": dict(
            attrs=attrs_1b, batch=1, seq=mc["seq"], ce=mc["ce"],
            tkg=mc["tkg"], prompt=mc["prompt"], gen=mc["gen"],
            long_prompt=None, quantized=False,
            extra_tpu=dict(kv_cache_dtype="int8"),
            cache_key="bf16_1b" if not tiny else None,
        ),
        "bf16_1b_16k": dict(
            attrs=attrs_1b, batch=1, seq=lc["seq"], ce=lc["ce"],
            tkg=lc["tkg"], prompt=lc["prompt"], gen=lc["gen"],
            long_prompt=None, quantized=False,
            cache_key="bf16_1b" if not tiny else None,
        ),
        "bf16_1b_16k_kvq8": dict(
            attrs=attrs_1b, batch=1, seq=lc["seq"], ce=lc["ce"],
            tkg=lc["tkg"], prompt=lc["prompt"], gen=lc["gen"],
            long_prompt=None, quantized=False,
            extra_tpu=dict(kv_cache_dtype="int8"),
            cache_key="bf16_1b" if not tiny else None,
        ),
    }


def _attach_projection(res, attrs, *, batch, kv_width, quantized, extra_tpu,
                       scale=1):
    """Static roofline projection beside the measured row (ISSUE 11):
    ``projected_tok_s`` is the device-model lower-bound ceiling for this
    row's shape on the RESOLVED chip (falls back to the registry default on
    an unresolvable device, e.g. the CPU harness), and ``model_error_frac``
    = measured/projected - 1 — null when the device didn't resolve, since
    an error against a chip the run never touched means nothing.

    ``scale``: aggregate multiplier for multi-mesh rows (the router point
    passes the count of NON-overlapping replica meshes — replicas sharing
    one chip split its HBM stream and add no ceiling). Applied only when
    the device RESOLVES to a registry chip: the CPU harness's virtual
    partitions share one host, so its projection stays the committed
    single-chip number (`device_model.BENCH_ROW_MODELS` / --compare)."""
    import jax

    from neuronx_distributed_inference_tpu.analysis import device_model

    spec = device_model.resolve_device(
        getattr(jax.devices()[0], "device_kind", "") or str(jax.devices()[0])
    )
    proj = device_model.decode_projection(
        attrs,
        batch=batch,
        kv_width=kv_width,
        # explicit weight_dtype (the int4 rows) wins over the quantized flag
        weight_dtype=(extra_tpu or {}).get(
            "weight_dtype", "int8" if quantized else "bfloat16"
        ),
        kv_dtype=(extra_tpu or {}).get("kv_cache_dtype", "bfloat16"),
        device=spec,  # None -> DEFAULT_DEVICE inside
    )
    projected = proj["tok_s"] * (scale if spec is not None else 1)
    res["projected_tok_s"] = round(projected, 2)
    res["model_error_frac"] = (
        round(res["decode_tok_s"] / projected - 1.0, 4)
        if spec is not None and res.get("decode_tok_s")
        else None
    )
    return res


def run_point(name, tiny=False):
    """Build + measure one benchmark point in THIS process."""
    import jax

    p = _suite_params(tiny)[name]

    def _disagg_fleet(s, n_decode):
        """(decode apps, prefill apps) for a disaggregated-tier row: the
        hand-off scatters whole cache lines, so BOTH stages run the
        CONTIGUOUS cache (no block_kv); each replica gets its own device
        partition, prefill replicas after the decode ones."""
        from neuronx_distributed_inference_tpu.runtime.router import (
            partition_devices,
        )

        n_pre = p["disagg"]["prefill_replicas"]
        parts = partition_devices(n_decode + n_pre)
        contiguous = dict(is_continuous_batching=True, ctx_batch_size=1)
        ck = p.get("cache_key")
        decode = [
            build_app(
                p["attrs"], batch=s["max_seqs"], seq_len=s["seq"],
                ce_buckets=[s["seq"]], tkg_buckets=[s["seq"]],
                quantized=p["quantized"], cache_key=ck,
                extra_tpu={**contiguous, **(p.get("extra_tpu") or {})},
                devices=parts[i],
            )
            for i in range(n_decode)
        ]
        prefill = [
            build_app(
                p["attrs"], batch=s["max_seqs"], seq_len=s["seq"],
                ce_buckets=[s["seq"]], tkg_buckets=[s["seq"]],
                quantized=p["quantized"],
                cache_key=f"{ck}_pre" if ck else None,
                extra_tpu={**contiguous, "is_prefill_stage": True,
                           **(p.get("extra_tpu") or {})},
                devices=parts[n_decode + i],
            )
            for i in range(n_pre)
        ]
        return decode, prefill

    if "workload" in p:
        from neuronx_distributed_inference_tpu.runtime.router import (
            partition_devices,
        )

        s = p["serving"]
        ch = p.get("chaos")
        n_apps = ch["replicas"] if ch else 1
        if "disagg" in p:
            apps, prefill_apps = _disagg_fleet(s, n_apps)
        else:
            prefill_apps = None
            parts = partition_devices(n_apps) if n_apps > 1 else [None]
            apps = [
                build_app(
                    p["attrs"], batch=s["max_seqs"], seq_len=s["seq"],
                    ce_buckets=[s["seq"]], tkg_buckets=[s["seq"]],
                    quantized=p["quantized"], cache_key=p.get("cache_key"),
                    block_kv=dict(num_blocks=s["blocks"],
                                  block_size=s["block_size"],
                                  max_seqs=s["max_seqs"]),
                    extra_tpu=p.get("extra_tpu"), devices=parts[i],
                )
                for i in range(n_apps)
            ]
        res = measure_goodput(
            apps, workload=p["workload"],
            chaos_kill_step=ch["kill_step"] if ch else None,
            chaos_tier=(ch or {}).get("tier", "decode"),
            prefill_apps=prefill_apps,
        )
        # same aggregate decode ceiling as the closed-loop serving rows:
        # goodput <= throughput <= the device projection
        _attach_projection(
            res, p["attrs"], batch=s["max_seqs"], kv_width=s["seq"],
            quantized=p["quantized"], extra_tpu=p.get("extra_tpu"),
        )
    elif "router" in p:
        from neuronx_distributed_inference_tpu.runtime.router import (
            partition_devices,
        )

        s, r = p["serving"], p["router"]
        if "disagg" in p:
            apps, prefill_apps = _disagg_fleet(s, r["replicas"])
            parts = partition_devices(
                r["replicas"] + p["disagg"]["prefill_replicas"]
            )[: r["replicas"]]
        else:
            prefill_apps = None
            parts = partition_devices(r["replicas"])
            apps = [
                build_app(
                    p["attrs"], batch=s["max_seqs"], seq_len=s["seq"],
                    ce_buckets=[s["seq"]], tkg_buckets=[s["seq"]],
                    quantized=p["quantized"], cache_key=p.get("cache_key"),
                    block_kv=dict(num_blocks=s["blocks"],
                                  block_size=s["block_size"],
                                  max_seqs=s["max_seqs"]),
                    extra_tpu=p.get("extra_tpu"), devices=parts[i],
                )
                for i in range(r["replicas"])
            ]
        res = measure_router(
            apps, n_requests=r["n_requests"], prompt_len=s["prompt"],
            gen_len=s["gen"], policy=r["policy"],
            prefill_apps=prefill_apps, elastic=p.get("elastic"),
        )
        # router ceiling: each replica serves its share of the mix and
        # streams its OWN weight copy, so the aggregate scales with the
        # number of non-overlapping replica meshes (1 on a shared chip,
        # = replicas when each replica has its own chip/partition)
        distinct = len({d.id for part in parts for d in part})
        meshes = max(1, distinct // max(1, len(parts[0])))
        rows_per_replica = max(1, r["n_requests"] // r["replicas"])
        _attach_projection(
            res, p["attrs"], batch=rows_per_replica, kv_width=s["seq"],
            quantized=p["quantized"], extra_tpu=p.get("extra_tpu"),
            scale=min(meshes, r["replicas"]),
        )
    elif "spec" in p:
        from neuronx_distributed_inference_tpu.analysis import device_model

        s, sp = p["serving"], p["spec"]
        k = sp["speculation_length"]
        target = build_app(
            p["attrs"], batch=s["max_seqs"], seq_len=s["seq"],
            ce_buckets=[s["seq"]], tkg_buckets=[s["seq"]],
            quantized=p["quantized"], cache_key=p.get("cache_key"),
            block_kv=dict(num_blocks=s["blocks"], block_size=s["block_size"],
                          max_seqs=s["max_seqs"], q_tile=s.get("q_tile", 128)),
            extra_tpu=p.get("extra_tpu"),
        )
        # the DRAFT app: contiguous cache, same slot count / decode reach
        # (the spec session's construction contract)
        draft = build_app(
            sp["draft_attrs"], batch=s["max_seqs"], seq_len=s["seq"],
            ce_buckets=[s["seq"]], tkg_buckets=[s["seq"]],
            quantized=p["quantized"], cache_key=sp.get("draft_cache_key"),
            extra_tpu=dict(is_continuous_batching=True, ctx_batch_size=1),
        )
        res = measure_serving_spec(
            target, draft, n_requests=s["n_requests"], prompt_len=s["prompt"],
            gen_len=s["gen"], k=k,
        )
        # acceptance-parameterized ceiling (ISSUE 12): re-projected at the
        # MEASURED acceptance rate so the recorded ceiling describes the
        # workload this run actually saw (falls back to the committed 0.8
        # operating point when no spec round ran)
        spec_dev = device_model.resolve_device(
            getattr(jax.devices()[0], "device_kind", "") or str(jax.devices()[0])
        )
        proj = device_model.spec_decode_projection(
            p["attrs"], batch=s["max_seqs"], kv_width=s["seq"],
            acceptance=(
                res["spec_acceptance"] if res.get("spec_acceptance") is not None
                else 0.8
            ),
            draft_len=k - 1, draft_attrs=sp["draft_attrs"],
            weight_dtype="int8" if p["quantized"] else "bfloat16",
            kv_dtype=(p.get("extra_tpu") or {}).get("kv_cache_dtype", "bfloat16"),
            device=spec_dev,
        )
        res["projected_tok_s"] = round(proj["tok_s"], 2)
        res["model_error_frac"] = (
            round(res["decode_tok_s"] / proj["tok_s"] - 1.0, 4)
            if spec_dev is not None and res.get("decode_tok_s")
            else None
        )
    elif "serving" in p:
        s = p["serving"]
        app = build_app(
            p["attrs"], batch=s["max_seqs"], seq_len=s["seq"],
            ce_buckets=[s["seq"]], tkg_buckets=[s["seq"]],
            quantized=p["quantized"], cache_key=p.get("cache_key"),
            block_kv=dict(num_blocks=s["blocks"], block_size=s["block_size"],
                          max_seqs=s["max_seqs"]),
            extra_tpu=p.get("extra_tpu"),
        )
        res = measure_serving(
            app, n_requests=s["n_requests"], prompt_len=s["prompt"],
            gen_len=s["gen"],
        )
        # aggregate decode ceiling at the full slot count / serving bucket
        _attach_projection(
            res, p["attrs"], batch=s["max_seqs"], kv_width=s["seq"],
            quantized=p["quantized"], extra_tpu=p.get("extra_tpu"),
        )
    else:
        app = build_app(
            p["attrs"], batch=p["batch"], seq_len=p["seq"], ce_buckets=p["ce"],
            tkg_buckets=p["tkg"], quantized=p["quantized"],
            cache_key=p.get("cache_key"), extra_tpu=p.get("extra_tpu"),
        )
        res = measure_point(
            app, batch=p["batch"], prompt_len=p["prompt"], gen_len=p["gen"],
            long_prompt=p["long_prompt"],
        )
        # the measured decode runs at the bucket covering prompt+gen
        ctx = p["prompt"] + p["gen"]
        kv_w = min([b for b in p["tkg"] if b >= ctx] or [max(p["tkg"])])
        _attach_projection(
            res, p["attrs"], batch=p["batch"], kv_width=kv_w,
            quantized=p["quantized"], extra_tpu=p.get("extra_tpu"),
        )
    res["device"] = str(jax.devices()[0])
    return res


def summary_line(points):
    """The machine-readable summary over whatever points exist so far.
    Keys are stable; not-yet-run points contribute null fields."""

    def g(name, key):
        return points.get(name, {}).get(key)

    headline = g("bf16_1b_bs1", "decode_tok_s")
    return {
        "metric": "llama3.2-1b-bf16 decode throughput (bs=1, 1 chip)",
        "value": headline,
        "unit": "tokens/sec",
        "vs_baseline": (
            round(headline / BASELINE_1B, 4) if headline else None
        ),
        # static roofline projection (ISSUE 11): the device-model ceiling
        # for the headline row and its measured error — model_error_frac is
        # null on a host whose device doesn't resolve to a registry spec
        # (the CPU harness) and populated on hardware
        "projected_tok_s": g("bf16_1b_bs1", "projected_tok_s"),
        "model_error_frac": g("bf16_1b_bs1", "model_error_frac"),
        "ttft_ms": g("bf16_1b_bs1", "ttft_ms"),
        "prefill_tok_s": g("bf16_1b_bs1", "prefill_tok_s"),
        "decode_bs4_tok_s": g("bf16_1b_bs4", "decode_tok_s"),
        "int8_1b_tok_s": g("int8_1b_bs1", "decode_tok_s"),
        "int8_1b_ttft_ms": g("int8_1b_bs1", "ttft_ms"),
        "serving_tok_s": g("serving_1b_int8", "decode_tok_s"),
        # the serving rows' aggregate device ceiling + measured error: the
        # measured-vs-predicted pair hardware session zero closes on (the
        # CPU harness carries the projection with a null error)
        "serving_projected_tok_s": g("serving_1b_int8", "projected_tok_s"),
        "serving_model_error_frac": g("serving_1b_int8", "model_error_frac"),
        # TTFT/ITL sourced from the runtime telemetry traces (not bench
        # stopwatches): the numbers production serving would report
        "serving_ttft_p50_ms": g("serving_1b_int8", "ttft_ms"),
        "serving_ttft_p99_ms": g("serving_1b_int8", "ttft_p99_ms"),
        "serving_itl_p50_ms": g("serving_1b_int8", "itl_ms"),
        "serving_itl_p99_ms": g("serving_1b_int8", "itl_p99_ms"),
        # ragged mixed-step serving row (ISSUE 6): same request mix, ONE
        # ragged dispatch per step — compare against serving_* above; the
        # padded-token fraction quantifies the packing efficiency the
        # per-phase split was throwing away
        "ragged_tok_s": g("serving_1b_int8_ragged", "decode_tok_s"),
        "ragged_ttft_p50_ms": g("serving_1b_int8_ragged", "ttft_ms"),
        "ragged_ttft_p99_ms": g("serving_1b_int8_ragged", "ttft_p99_ms"),
        "ragged_itl_p50_ms": g("serving_1b_int8_ragged", "itl_ms"),
        "ragged_itl_p99_ms": g("serving_1b_int8_ragged", "itl_p99_ms"),
        "ragged_padded_frac": g("serving_1b_int8_ragged", "padded_token_frac"),
        # async-pipelined ragged serving row (ISSUE 8): same mix, 1-ahead
        # chained dispatch + non-blocking fetch — compare against the
        # ragged_* (sync) row; serving_host_frac is the measured host-gap
        # share of step wall time on the pipelined path
        "ragged_async_tok_s": g("serving_1b_int8_ragged_async", "decode_tok_s"),
        "ragged_async_itl_p50_ms": g("serving_1b_int8_ragged_async", "itl_ms"),
        "ragged_async_ttft_p50_ms": g("serving_1b_int8_ragged_async", "ttft_ms"),
        "serving_host_frac": g("serving_1b_int8_ragged_async", "host_frac"),
        # spec-ragged serving row (ISSUE 12): verification inside the mixed
        # dispatch. spec_ragged_acceptance is the MEASURED per-draft
        # acceptance rate (random weights => ~0: the worst-case overhead
        # bound); spec_ragged_projected_tok_s is the acceptance-
        # parameterized ceiling re-projected at that measured rate, which
        # --compare prefers over the static 0.8-acceptance table row
        "spec_ragged_tok_s": g("serving_1b_int8_spec_ragged", "decode_tok_s"),
        "spec_ragged_acceptance": g("serving_1b_int8_spec_ragged",
                                    "spec_acceptance"),
        "spec_ragged_itl_p50_ms": g("serving_1b_int8_spec_ragged", "itl_ms"),
        "spec_ragged_projected_tok_s": g("serving_1b_int8_spec_ragged",
                                         "projected_tok_s"),
        # fault-containment census (ISSUE 7), sourced from the telemetry
        # registry over the measured serving run: clean traffic MUST report
        # 0/0/0 — the containment layer's ~0-overhead proof the first
        # hardware session checks before flipping any policy knob
        "serving_rejected": g("serving_1b_int8", "rejected"),
        "serving_quarantined": g("serving_1b_int8", "quarantined"),
        "serving_preempted": g("serving_1b_int8", "preempted"),
        # multi-replica router row (ISSUE 10): same mix over 2 replica
        # sessions via ServingRouter — router_failover MUST be 0 on clean
        # traffic (per-run delta, PR 7 convention) and router_balance_frac
        # (min-replica tokens / even share) is the placement-policy quality
        # number the first multi-chip session compares policies by
        "router_tok_s": g("serving_1b_int8_router", "decode_tok_s"),
        # the router row's projection carries its mesh-count scaling, which
        # the static --compare table cannot know — recorded here so the
        # offline report uses the run's own ceiling
        "router_projected_tok_s": g("serving_1b_int8_router", "projected_tok_s"),
        "router_failover": g("serving_1b_int8_router", "failover"),
        "router_balance_frac": g("serving_1b_int8_router", "balance_frac"),
        # thread-per-replica router row (ISSUE 13): same routed mix with
        # router_threading on — compare router_threaded_tok_s against
        # router_tok_s for the threading win, and router_step_overlap_frac
        # (replica-step histograms vs the router-step span) for how much of
        # the per-replica step wall actually ran concurrently. On a 1-chip
        # host both replicas share the device, so the overlap a chip-per-
        # replica deployment would convert to tok/s is the hardware
        # session's number to confirm.
        # disaggregated prefill tier (ISSUE 15): the routed mix with every
        # prompt context-encoded on a dedicated prefill replica and handed
        # over; clean traffic pins handoffs == requests and ZERO hand-off
        # failures / local-prefill fallbacks, and the chaos row pins
        # containment under a prefill-tier kill
        "disagg_tok_s": g("serving_1b_int8_disagg", "decode_tok_s"),
        "disagg_handoffs": g("serving_1b_int8_disagg", "handoffs"),
        "disagg_handoff_failures": g("serving_1b_int8_disagg",
                                     "handoff_failures"),
        "disagg_local_prefill": g("serving_1b_int8_disagg",
                                  "handoff_local_prefill"),
        "disagg_chaos_goodput_tok_s": g("serving_1b_int8_disagg_chaos",
                                        "goodput_tok_s"),
        "disagg_chaos_attainment": g("serving_1b_int8_disagg_chaos",
                                     "slo_attainment"),
        "disagg_chaos_local_prefill": g("serving_1b_int8_disagg_chaos",
                                        "handoff_local_prefill"),
        "disagg_chaos_dip_frac": g("serving_1b_int8_disagg_chaos",
                                   "goodput_dip_frac"),
        "disagg_chaos_recovery_steps": g("serving_1b_int8_disagg_chaos",
                                         "goodput_recovery_steps"),
        "router_threaded_tok_s": g("serving_1b_int8_router_threaded",
                                   "decode_tok_s"),
        "router_step_overlap_frac": g("serving_1b_int8_router_threaded",
                                      "overlap_frac"),
        # elastic fleet row (ISSUE 20): seeded retire + add mid-drain —
        # attainment MUST be 1.0 with ZERO leaked KV blocks/threads (the
        # lifecycle audit's leak-freedom contract, measured)
        "elastic_tok_s": g("serving_1b_int8_elastic", "decode_tok_s"),
        "elastic_attainment": g("serving_1b_int8_elastic",
                                "elastic_attainment"),
        "elastic_leaked_blocks": g("serving_1b_int8_elastic",
                                   "elastic_leaked_blocks"),
        "elastic_leaked_threads": g("serving_1b_int8_elastic",
                                    "elastic_leaked_threads"),
        # open-loop SLO goodput rows (ISSUE 14, docs/WORKLOADS.md):
        # goodput_tok_s counts ONLY tokens from requests that met their
        # TTFT/ITL SLOs (measured from arrival — backlog wait counts);
        # slo_attainment pins 1.0 on the clean generous-SLO row; the chaos
        # row reads the seeded replica kill off the time-bucketed goodput
        # series as dip depth + recovery steps
        "goodput_tok_s": g("serving_1b_int8_goodput", "goodput_tok_s"),
        "slo_attainment": g("serving_1b_int8_goodput", "slo_attainment"),
        "goodput_burst_tok_s": g("serving_1b_int8_goodput_burst",
                                 "goodput_tok_s"),
        "goodput_burst_attainment": g("serving_1b_int8_goodput_burst",
                                      "slo_attainment"),
        "goodput_backlog_refusals": g("serving_1b_int8_goodput_burst",
                                      "backlog_refusals"),
        "goodput_chaos_tok_s": g("serving_1b_int8_goodput_chaos",
                                 "goodput_tok_s"),
        "goodput_dip_frac": g("serving_1b_int8_goodput_chaos",
                              "goodput_dip_frac"),
        "goodput_recovery_steps": g("serving_1b_int8_goodput_chaos",
                                    "goodput_recovery_steps"),
        "int8_8b_tok_s": g("int8_8b_bs1", "decode_tok_s"),
        "int8_8b_ttft_ms": g("int8_8b_bs1", "ttft_ms"),
        # grouped-int4 weight-streaming rows (ISSUE 17): the 8B decode pair
        # against int8_8b_tok_s quantifies the weight-bandwidth halving
        # (~0.53 vs 1 byte/param), and the int4 ragged serving row sits
        # beside ragged_tok_s for the mixed-load version. Projections ride
        # the device model's int4 itemsize (codes + group scales).
        "w4_tok_s": g("bf16_8b_int4", "decode_tok_s"),
        "w4_projected_tok_s": g("bf16_8b_int4", "projected_tok_s"),
        "w4_ttft_ms": g("bf16_8b_int4", "ttft_ms"),
        "w4_serving_tok_s": g("serving_1b_int4_ragged", "decode_tok_s"),
        "w4_serving_projected_tok_s": g("serving_1b_int4_ragged",
                                        "projected_tok_s"),
        "w4_serving_itl_p50_ms": g("serving_1b_int4_ragged", "itl_ms"),
        # 16k long-context row: TTFT ~= the 16k prefill wall time
        "long_ctx_ttft_ms": g("bf16_1b_16k", "ttft_ms"),
        "long_ctx_tok_s": g("bf16_1b_16k", "decode_tok_s"),
        # 8k/16k bf16 vs kv-int8 pairs: decode tok/s + true cache bytes
        # (codes + scales) — the *_kvq8 rows must show kv_bytes ~halved
        "ctx8k_tok_s": g("bf16_1b_8k", "decode_tok_s"),
        "ctx8k_kv_bytes": g("bf16_1b_8k", "kv_bytes"),
        "kvq8_8k_tok_s": g("bf16_1b_8k_kvq8", "decode_tok_s"),
        "kvq8_8k_kv_bytes": g("bf16_1b_8k_kvq8", "kv_bytes"),
        "long_ctx_kv_bytes": g("bf16_1b_16k", "kv_bytes"),
        "kvq8_16k_tok_s": g("bf16_1b_16k_kvq8", "decode_tok_s"),
        "kvq8_16k_ttft_ms": g("bf16_1b_16k_kvq8", "ttft_ms"),
        "kvq8_16k_kv_bytes": g("bf16_1b_16k_kvq8", "kv_bytes"),
        "int8_8b_vs_8b_gate": (
            round(g("int8_8b_bs1", "decode_tok_s") / BASELINE_8B_GATE, 4)
            if g("int8_8b_bs1", "decode_tok_s")
            else None
        ),
        "points": {
            n: ("ok" if "decode_tok_s" in p else
                "skipped_budget" if p.get("skipped_budget") else "error")
            for n, p in points.items()
        },
        "device": g("bf16_1b_bs1", "device"),
    }


def _emit(points):
    print(json.dumps(summary_line(points)), flush=True)


def run_suite(tiny=False, emit=None):
    """The full benchmark point set. ``tiny=True`` runs in-process (the CPU
    test suite exercises the identical code path in seconds); otherwise each
    point runs in its own subprocess — the TPU lease is per-process and HBM is
    fully reclaimed between points (an int8 8B point cannot share a 16G chip
    with an earlier resident 1B model).

    ``emit``: callback invoked with the points dict after every point — suite
    mode uses it to re-print the summary line so a driver-side kill at ANY
    moment still leaves a parseable last line (VERDICT r4 #1).
    """
    points = {}
    names = list(_suite_params(tiny))
    budget = _budget_s()
    t_start = time.monotonic()
    if tiny:
        for name in names:
            if name != names[0] and time.monotonic() - t_start > budget:
                points[name] = {"skipped_budget": True}
            else:
                points[name] = run_point(name, tiny=True)
            if emit:
                emit(points)
        return points
    import subprocess

    for name in names:
        elapsed = time.monotonic() - t_start
        if name != names[0] and elapsed > budget:
            points[name] = {"skipped_budget": True, "elapsed_s": round(elapsed, 1)}
            print(f"{name}: skipped (budget {budget:.0f}s)", file=sys.stderr)
            if emit:
                emit(points)
            continue
        # the headline point always gets the full budget; later points get
        # what remains (+ grace — a point that STARTED may finish slightly
        # over budget rather than be killed uselessly)
        remaining = budget if name == names[0] else budget - elapsed
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--point", name],
                capture_output=True, text=True,
                timeout=max(120.0, remaining + 180.0),
            )
            if proc.returncode != 0:
                print(proc.stderr[-4000:], file=sys.stderr)
                raise RuntimeError(f"bench point {name} failed rc={proc.returncode}")
            points[name] = json.loads(proc.stdout.strip().splitlines()[-1])
        except Exception as e:  # timeout / crash / bad output
            # a timed-out child's partial stderr is the only diagnostic left
            partial = getattr(e, "stderr", None)
            if partial:
                if isinstance(partial, bytes):
                    partial = partial.decode(errors="replace")
                print(partial[-4000:], file=sys.stderr)
            if name == names[0]:
                raise  # no headline -> the suite IS failed
            points[name] = {"error": str(e)[:200]}
        print(f"{name}: {points[name]}", file=sys.stderr)
        if emit:
            emit(points)
    return points


def _trace_out_path():
    """--trace-out PATH: Chrome trace-event JSON (Perfetto-loadable) of the
    goodput rows' span timeline, written by the measured pass of each
    ``measure_goodput`` call in THIS process (pass it to a --point
    invocation of a goodput row; docs/OBSERVABILITY.md walks the file)."""
    if "--trace-out" in sys.argv:
        i = sys.argv.index("--trace-out")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return None


def _metrics_out_path():
    """--metrics-out PATH: dump THIS process's telemetry registry snapshot
    at exit (tiny/--point runs carry the serving metrics; the non-tiny suite
    driver itself runs no model, so point subprocesses are where the data
    lives — pass --metrics-out to a --point invocation for a full dump)."""
    if "--metrics-out" in sys.argv:
        i = sys.argv.index("--metrics-out")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return None


def _dump_metrics(path):
    from neuronx_distributed_inference_tpu.telemetry import default_registry

    with open(path, "w") as f:
        json.dump(default_registry().snapshot(), f, indent=2)
    print(f"metrics snapshot -> {path}", file=sys.stderr)


def _ops_server():
    """--ops-port N: serve this process's live ops surface (/metrics,
    /healthz, /slo — docs/OBSERVABILITY.md) off the process-default registry
    for the duration of the run. Returned as a context manager so the serve
    thread is JOINED even when the run raises mid-drain (the LIFE804
    thread-lifecycle contract); without the flag it is a no-op context."""
    import contextlib

    if "--ops-port" in sys.argv:
        i = sys.argv.index("--ops-port")
        if i + 1 < len(sys.argv):
            from neuronx_distributed_inference_tpu.telemetry import default_registry
            from neuronx_distributed_inference_tpu.telemetry.ops_server import (
                OpsServer,
            )

            return OpsServer(default_registry(), port=int(sys.argv[i + 1]))
    return contextlib.nullcontext()


def main():
    if "--cpu" in sys.argv:
        # the container sitecustomize pins jax_platforms to the TPU plugin;
        # only the config update (not the env var) overrides it
        import jax

        jax.config.update("jax_platforms", "cpu")
    metrics_out = _metrics_out_path()
    with _ops_server() as ops:
        if ops is not None:
            print(f"ops server -> {ops.url}", file=sys.stderr)
        if len(sys.argv) >= 3 and sys.argv[1] == "--point":
            _wait_for_backend()
            print(json.dumps(run_point(sys.argv[2], tiny=False)))
            if metrics_out:
                _dump_metrics(metrics_out)
            return
        tiny = "--tiny" in sys.argv
        # suite mode (non-tiny): do NOT touch the TPU here — the lease is
        # per-process and each point's subprocess needs it
        run_suite(tiny=tiny, emit=_emit)
        if metrics_out:
            _dump_metrics(metrics_out)


if __name__ == "__main__":
    main()
