#!/usr/bin/env python
"""Benchmark driver entry: prints ONE JSON line with the headline metric.

Measures steady-state decode throughput (tokens/sec) for a Llama-3.2-1B-shaped
model (full size, random weights, bf16) on the available chip, mirroring the
reference's benchmark_sampling metric definitions
(reference: utils/benchmark.py:479-499 — throughput = runs·tokens·batch/total).

vs_baseline anchors against the reference's Llama3.2-1B-class integration
throughput gate (~1057 tok/s on 32 trainium cores,
test_llama3_2_1b_4layer_context_parallel.py:36-44). We run on ONE v5e chip,
so >1.0 means one TPU chip beats the 32-core trn gate.
"""

import json
import sys
import time


def _wait_for_backend(max_wait_s=300):
    """The TPU lease is exclusive per-process and can take minutes to free."""
    import jax

    deadline = time.time() + max_wait_s
    while True:
        try:
            devs = jax.devices()
            return devs
        except RuntimeError as e:
            if time.time() > deadline:
                raise
            print(f"waiting for TPU backend: {e}", file=sys.stderr)
            time.sleep(15)
            # jax caches backend init failure; clear and retry
            try:
                from jax.extend.backend import clear_backends

                clear_backends()
            except Exception:
                pass


def main():
    devs = _wait_for_backend()
    import numpy as np

    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig
    from neuronx_distributed_inference_tpu.runtime.application import TpuModelForCausalLM

    hf_attrs = dict(
        model_type="llama",
        hidden_size=2048,
        intermediate_size=8192,
        num_attention_heads=32,
        num_key_value_heads=8,
        num_hidden_layers=16,
        vocab_size=128256,
        rms_norm_eps=1e-5,
        rope_theta=500000.0,
        max_position_embeddings=2048,
        hidden_act="silu",
        tie_word_embeddings=True,
        head_dim=64,
    )

    def load_cfg(c):
        for k, v in hf_attrs.items():
            setattr(c, k, v)

    batch, seq_len, prompt_len, gen_len = 1, 1024, 128, 256
    long_prompt = 512  # prefill-throughput point (amortizes the relay sync)
    tc = TpuConfig(
        batch_size=batch,
        seq_len=seq_len,
        dtype="bfloat16",
        enable_bucketing=True,
        context_encoding_buckets=[prompt_len, long_prompt],
        token_generation_buckets=[512, 1024],
    )
    cfg = LlamaInferenceConfig(tc, load_config=load_cfg)
    app = TpuModelForCausalLM(None, cfg)
    app.load(random_weights=True)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 120000, size=(batch, prompt_len))
    mask = np.ones_like(ids)
    ids_long = rng.randint(0, 120000, size=(batch, long_prompt))
    mask_long = np.ones_like(ids_long)

    # warmup / compile — run the SAME programs the measured runs use
    # (gen_len-sized decode chunk and the 1-token TTFT path)
    t0 = time.time()
    app.generate(ids, mask, max_new_tokens=gen_len)
    app.generate(ids, mask, max_new_tokens=1)
    app.generate(ids_long, mask_long, max_new_tokens=1)
    print(f"compile+warmup: {time.time()-t0:.1f}s", file=sys.stderr)

    # TTFT: context encoding only
    t0 = time.time()
    app.generate(ids, mask, max_new_tokens=1)
    ttft_ms = (time.time() - t0) * 1e3

    # prefill throughput: 512-token CTE (sync cost amortized over the prompt)
    t0 = time.time()
    app.generate(ids_long, mask_long, max_new_tokens=1)
    prefill_tok_s = long_prompt / (time.time() - t0)

    # decode throughput (headline)
    t0 = time.time()
    out = app.generate(ids, mask, max_new_tokens=gen_len)
    total = time.time() - t0
    throughput = out.num_generated * batch / total

    # batched decode point (continuous-batching shape; VERDICT r2 weak #3)
    bs4 = 4
    tc4 = TpuConfig(
        batch_size=bs4, seq_len=seq_len, dtype="bfloat16",
        enable_bucketing=True, context_encoding_buckets=[prompt_len],
        token_generation_buckets=[512],
    )
    app4 = TpuModelForCausalLM(None, LlamaInferenceConfig(tc4, load_config=load_cfg))
    app4.load(random_weights=True)
    ids4 = rng.randint(0, 120000, size=(bs4, prompt_len))
    mask4 = np.ones_like(ids4)
    app4.generate(ids4, mask4, max_new_tokens=gen_len)  # compile+warm
    t0 = time.time()
    out4 = app4.generate(ids4, mask4, max_new_tokens=gen_len)
    decode_bs4 = out4.num_generated * bs4 / (time.time() - t0)

    baseline = 1057.0  # reference 1B-class 32-core gate (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": "llama3.2-1b-bf16 decode throughput (bs=1, 1 chip)",
                "value": round(throughput, 2),
                "unit": "tokens/sec",
                "vs_baseline": round(throughput / baseline, 4),
                "ttft_ms": round(ttft_ms, 1),
                "prefill_tok_s": round(prefill_tok_s, 1),
                "decode_bs4_tok_s": round(decode_bs4, 2),
                "device": str(devs[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
