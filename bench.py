#!/usr/bin/env python
"""Benchmark driver entry: prints ONE JSON line with the headline metric.

Measures steady-state decode throughput (tokens/sec) on the available chip for
full-size random-weight models, mirroring the reference's benchmark_sampling
metric definitions (reference: utils/benchmark.py:479-499 —
throughput = runs·tokens·batch/total).

Points (VERDICT r3 next-steps #1/#3):
- llama-3.2-1B bf16: bs=1 decode (headline), TTFT, 512-token prefill, bs=4 decode
- llama-3.2-1B int8: bs=1 decode + TTFT (HBM-bound decode ⇒ int8 halves traffic)
- llama-3.1-8B int8: bs=1 decode + TTFT (the closest single-chip proxy for the
  BASELINE.json 8B north star; int8 8B fits one 16G v5e chip)

vs_baseline anchors against the reference's Llama3.2-1B-class integration
throughput gate (~1057 tok/s on 32 trainium cores,
test_llama3_2_1b_4layer_context_parallel.py:36-44). We run on ONE v5e chip,
so >1.0 means one TPU chip beats the 32-core trn gate.

The whole measurement path (build → load → warmup → measure) is importable and
size-parameterized so the test suite smoke-runs the EXACT code path on CPU
(tests/test_bench_smoke.py) — two of three rounds shipped a bench-only crash
the suite missed (VERDICT r3 weak #2).
"""

import json
import sys
import time

LLAMA_1B = dict(
    model_type="llama",
    hidden_size=2048,
    intermediate_size=8192,
    num_attention_heads=32,
    num_key_value_heads=8,
    num_hidden_layers=16,
    vocab_size=128256,
    rms_norm_eps=1e-5,
    rope_theta=500000.0,
    max_position_embeddings=2048,
    hidden_act="silu",
    tie_word_embeddings=True,
    head_dim=64,
)

LLAMA_8B = dict(
    model_type="llama",
    hidden_size=4096,
    intermediate_size=14336,
    num_attention_heads=32,
    num_key_value_heads=8,
    num_hidden_layers=32,
    vocab_size=128256,
    rms_norm_eps=1e-5,
    rope_theta=500000.0,
    max_position_embeddings=2048,
    hidden_act="silu",
    tie_word_embeddings=False,
    head_dim=128,
)

TINY = dict(  # smoke-test model (CPU suite)
    model_type="llama",
    hidden_size=64,
    intermediate_size=128,
    num_attention_heads=4,
    num_key_value_heads=2,
    num_hidden_layers=2,
    vocab_size=128,
    rms_norm_eps=1e-5,
    rope_theta=10000.0,
    max_position_embeddings=256,
    hidden_act="silu",
    tie_word_embeddings=False,
)


def _wait_for_backend(max_wait_s=300):
    """The TPU lease is exclusive per-process and can take minutes to free."""
    import jax

    deadline = time.time() + max_wait_s
    while True:
        try:
            devs = jax.devices()
            return devs
        except RuntimeError as e:
            if time.time() > deadline:
                raise
            print(f"waiting for TPU backend: {e}", file=sys.stderr)
            time.sleep(15)
            # jax caches backend init failure; clear and retry
            try:
                from jax.extend.backend import clear_backends

                clear_backends()
            except Exception:
                pass


def build_app(
    hf_attrs,
    *,
    batch,
    seq_len,
    ce_buckets,
    tkg_buckets,
    dtype="bfloat16",
    quantized=False,
):
    """Build + load a random-weight app — the exact production code path."""
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    def load_cfg(c):
        for k, v in hf_attrs.items():
            setattr(c, k, v)

    tc = TpuConfig(
        batch_size=batch,
        seq_len=seq_len,
        dtype=dtype,
        enable_bucketing=True,
        context_encoding_buckets=list(ce_buckets),
        token_generation_buckets=list(tkg_buckets),
        quantized=quantized,
        # fused decode-layer kernels need the fused QKV weight layout; with it
        # they auto-enable on TPU (quantized configs fall back structurally)
        fused_qkv=not quantized,
    )
    app = TpuModelForCausalLM(None, LlamaInferenceConfig(tc, load_config=load_cfg))
    app.load(random_weights=True)
    return app


def measure_point(app, *, batch, prompt_len, gen_len, long_prompt=None):
    """Warmup-compile then measure TTFT / decode throughput (+ optional
    long-prompt prefill throughput). Returns a dict of metrics."""
    import numpy as np

    rng = np.random.RandomState(0)
    vocab = app.config.vocab_size - 10
    ids = rng.randint(0, vocab, size=(batch, prompt_len))
    mask = np.ones_like(ids)

    # warmup / compile — run the SAME programs the measured runs use
    # (gen_len-sized decode chunk and the 1-token TTFT path)
    t0 = time.time()
    app.generate(ids, mask, max_new_tokens=gen_len)
    app.generate(ids, mask, max_new_tokens=1)
    compile_s = time.time() - t0

    t0 = time.time()
    app.generate(ids, mask, max_new_tokens=1)
    ttft_ms = (time.time() - t0) * 1e3

    t0 = time.time()
    out = app.generate(ids, mask, max_new_tokens=gen_len)
    decode_tok_s = out.num_generated * batch / (time.time() - t0)

    res = {
        "ttft_ms": round(ttft_ms, 1),
        "decode_tok_s": round(decode_tok_s, 2),
        "compile_s": round(compile_s, 1),
    }
    if long_prompt:
        ids_l = rng.randint(0, vocab, size=(batch, long_prompt))
        mask_l = np.ones_like(ids_l)
        app.generate(ids_l, mask_l, max_new_tokens=1)  # compile
        t0 = time.time()
        app.generate(ids_l, mask_l, max_new_tokens=1)
        res["prefill_tok_s"] = round(long_prompt / (time.time() - t0), 1)
    return res


def _suite_params(tiny):
    if tiny:
        attrs_1b = attrs_8b = TINY
        prompt, gen, long_prompt = 16, 8, 32
        seq, ce, tkg = 64, [16, 32], [32, 64]
        ce4, tkg4 = [16], [32]
    else:
        attrs_1b, attrs_8b = LLAMA_1B, LLAMA_8B
        prompt, gen, long_prompt = 128, 256, 512
        seq, ce, tkg = 1024, [128, 512], [512, 1024]
        ce4, tkg4 = [128], [512]
    return {
        "bf16_1b_bs1": dict(
            attrs=attrs_1b, batch=1, seq=seq, ce=ce, tkg=tkg,
            prompt=prompt, gen=gen, long_prompt=long_prompt, quantized=False,
        ),
        "bf16_1b_bs4": dict(
            attrs=attrs_1b, batch=4, seq=seq, ce=ce4, tkg=tkg4,
            prompt=prompt, gen=gen, long_prompt=None, quantized=False,
        ),
        "int8_1b_bs1": dict(
            attrs=attrs_1b, batch=1, seq=seq, ce=ce[:1], tkg=tkg[:1],
            prompt=prompt, gen=gen, long_prompt=None, quantized=True,
        ),
        # single-chip proxy for the BASELINE 8B north star: int8 8B fits 16G
        "int8_8b_bs1": dict(
            attrs=attrs_8b, batch=1, seq=seq, ce=ce[:1], tkg=tkg[:1],
            prompt=prompt, gen=gen, long_prompt=None, quantized=True,
        ),
    }


def run_point(name, tiny=False):
    """Build + measure one benchmark point in THIS process."""
    import jax

    p = _suite_params(tiny)[name]
    app = build_app(
        p["attrs"], batch=p["batch"], seq_len=p["seq"], ce_buckets=p["ce"],
        tkg_buckets=p["tkg"], quantized=p["quantized"],
    )
    res = measure_point(
        app, batch=p["batch"], prompt_len=p["prompt"], gen_len=p["gen"],
        long_prompt=p["long_prompt"],
    )
    res["device"] = str(jax.devices()[0])
    return res


def run_suite(tiny=False):
    """The full benchmark point set. ``tiny=True`` runs in-process (the CPU
    test suite exercises the identical code path in seconds); otherwise each
    point runs in its own subprocess — the TPU lease is per-process and HBM is
    fully reclaimed between points (an int8 8B point cannot share a 16G chip
    with an earlier resident 1B model)."""
    points = {}
    if tiny:
        for name in _suite_params(True):
            points[name] = run_point(name, tiny=True)
        return points
    import subprocess

    for name in _suite_params(False):
        # generous per-point ceiling: the int8 8B point moves ~9 GB of
        # weights to the device, which through a tunneled chip is slow.
        # A failed/timed-out point must NOT sink the suite: the headline
        # (first) point's number is the contract — later points degrade to
        # an "error" entry in the JSON instead.
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--point", name],
                capture_output=True, text=True, timeout=7200,
            )
            if proc.returncode != 0:
                print(proc.stderr[-4000:], file=sys.stderr)
                raise RuntimeError(f"bench point {name} failed rc={proc.returncode}")
            points[name] = json.loads(proc.stdout.strip().splitlines()[-1])
        except Exception as e:  # timeout / crash / bad output
            # a timed-out child's partial stderr is the only diagnostic left
            partial = getattr(e, "stderr", None)
            if partial:
                if isinstance(partial, bytes):
                    partial = partial.decode(errors="replace")
                print(partial[-4000:], file=sys.stderr)
            if name == "bf16_1b_bs1":
                raise  # no headline -> the suite IS failed
            points[name] = {"error": str(e)[:200]}
        print(f"{name}: {points[name]}", file=sys.stderr)
    return points


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--point":
        _wait_for_backend()
        print(json.dumps(run_point(sys.argv[2], tiny=False)))
        return
    # suite mode: do NOT touch the TPU here — the lease is per-process and
    # each point's subprocess needs it
    points = run_suite(tiny=False)

    headline = points["bf16_1b_bs1"]["decode_tok_s"]
    baseline = 1057.0  # reference 1B-class 32-core gate (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": "llama3.2-1b-bf16 decode throughput (bs=1, 1 chip)",
                "value": headline,
                "unit": "tokens/sec",
                "vs_baseline": round(headline / baseline, 4),
                "ttft_ms": points["bf16_1b_bs1"]["ttft_ms"],
                "prefill_tok_s": points["bf16_1b_bs1"].get("prefill_tok_s"),
                "decode_bs4_tok_s": points["bf16_1b_bs4"].get("decode_tok_s"),
                "int8_1b_tok_s": points["int8_1b_bs1"].get("decode_tok_s"),
                "int8_1b_ttft_ms": points["int8_1b_bs1"].get("ttft_ms"),
                "int8_8b_tok_s": points["int8_8b_bs1"].get("decode_tok_s"),
                "int8_8b_ttft_ms": points["int8_8b_bs1"].get("ttft_ms"),
                # 1332 = reference 8B bf16 trn1-32-core throughput gate
                # (1665 * 0.8, BASELINE.md test_llama3_1_8b_4layer_dtype.py row)
                "int8_8b_vs_8b_gate": (
                    round(points["int8_8b_bs1"]["decode_tok_s"] / 1332.0, 4)
                    if "decode_tok_s" in points["int8_8b_bs1"]
                    else None
                ),
                "device": points["bf16_1b_bs1"].get("device"),
            }
        )
    )


if __name__ == "__main__":
    main()
