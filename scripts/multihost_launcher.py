#!/usr/bin/env python
"""Multi-host launcher: one process per host, SPMD over every chip.

Reference: scripts/nxdi_distributed_launcher.py:29-80 — the reference wraps
torchrun and re-execs per rank; on TPU the same job is
``jax.distributed.initialize`` + running the SAME single-host entrypoint on
every host. This launcher resolves the coordinator/world/rank triplet from
flags or the environment and then hands off to inference_demo (or any
``-m module``).

Usage (run on EVERY host):

    python scripts/multihost_launcher.py \
        --coordinator-address host0:8476 --num-processes 2 --process-id $RANK \
        -- -m neuronx_distributed_inference_tpu.inference_demo run \
           --model-path ... --tp-degree 8 ...

On Cloud TPU pod slices the triplet is auto-discovered; just run the same
command on every worker with no coordinator flags.
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator-address", default=os.environ.get("JAX_COORDINATOR_ADDRESS"))
    p.add_argument(
        "--num-processes",
        type=int,
        default=int(os.environ["JAX_NUM_PROCESSES"]) if "JAX_NUM_PROCESSES" in os.environ else None,
    )
    p.add_argument(
        "--process-id",
        type=int,
        default=int(os.environ["JAX_PROCESS_ID"]) if "JAX_PROCESS_ID" in os.environ else None,
    )
    p.add_argument("rest", nargs=argparse.REMAINDER, help="-- -m module args...")
    args = p.parse_args(argv)

    from neuronx_distributed_inference_tpu.parallel.mesh import initialize_multihost

    initialize_multihost(
        coordinator_address=args.coordinator_address,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )

    rest = args.rest
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        import jax

        print(
            f"[multihost] process {jax.process_index()}/{jax.process_count()} "
            f"sees {jax.device_count()} global devices"
        )
        return 0
    if rest[0] == "-m":
        sys.argv = [rest[1]] + rest[2:]
        runpy.run_module(rest[1], run_name="__main__")
        return 0
    sys.argv = rest
    runpy.run_path(rest[0], run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
