#!/usr/bin/env python
"""Prefill efficiency study: measured MFU for the context-encoding pass and
a flash-kernel block-size sweep (VERDICT r4 next #4 — "give prefill the
decode treatment"; reference CTE kernels sliding_window/attention.py:234,
chunked_prefill/flash_pa_with_schedule.py:157).

Two measurements per sequence length:
- whole-model CTE wall time AND device time (xplane trace): on a TUNNELED
  chip the wall clock includes host->device transfer + dispatch RTT, so
  device time is the honest MFU denominator;
- standalone flash-kernel timing across (bq, bkv) tile sizes — the tuning
  surface the whole-model number motivates.

MFU model (bf16 peak 197 TFLOP/s on v5e):
  matmul FLOPs/token = 2 * P_matmul  (P_matmul = params touched by matmuls)
  attention FLOPs    = 4 * L * S^2 * hidden * causal_factor(0.5)
Run on hardware: python scripts/prefill_profile.py
CPU smoke:       python scripts/prefill_profile.py --tiny --cpu
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

V5E_BF16_PEAK = 197e12


def _model_matmul_params(hf):
    H = hf["hidden_size"]
    I = hf["intermediate_size"]
    L = hf["num_hidden_layers"]
    V = hf["vocab_size"]
    Hq = hf["num_attention_heads"]
    Hkv = hf["num_key_value_heads"]
    D = hf.get("head_dim", H // Hq)
    attn = H * (Hq * D) + 2 * H * (Hkv * D) + (Hq * D) * H
    mlp = 3 * H * I
    # embedding lookup is a gather (no matmul); lm_head applies to ONE
    # position per row in prefill — negligible at large S
    return L * (attn + mlp)


def prefill_flops(hf, S):
    L = hf["num_hidden_layers"]
    H = hf["hidden_size"]
    matmul = 2 * _model_matmul_params(hf) * S
    attn = 4 * L * S * S * H * 0.5  # causal
    return matmul + attn


def measure_cte(app, S, hf, n=5, profile_dir=None):
    """Time the raw CTE runner at bucket S as a BURST: n dispatches chained
    on the donated cache, ONE value-fetch sync at the end — the relay RTT
    amortizes over n instead of polluting every run (NOT comparable to the
    r4 per-dispatch numbers, which each carried one RTT)."""
    import jax

    rng = np.random.RandomState(0)
    ids = rng.randint(0, hf["vocab_size"] - 10, size=(1, S))
    mask = np.ones_like(ids)
    pos = np.tile(np.arange(S, dtype=np.int32), (1, 1))
    runner = app.context_encoding_model
    inputs, _ = runner.prepare(ids, mask, pos, np.arange(1, dtype=np.int32))
    app.init_kv_cache()  # fresh buffers: earlier measurements donated them
    cache = [app.kv_cache]

    def dispatch():
        # the runner DONATES its cache argument; thread the returned cache
        # back as the next input (same buffers, device-resident)
        out = runner(app.params, cache[0], inputs, None)
        cache[0] = out.cache
        return out

    out = dispatch()  # compile
    jax.device_get(out.tokens)  # a VALUE fetch — block_until_ready has been
    # observed to return early on this experimental backend
    t0 = time.time()
    for _ in range(n):
        out = dispatch()
    jax.device_get(out.tokens)  # the chain serializes on the donated cache
    wall = (time.time() - t0) / n

    device_s = None
    ops = None
    if profile_dir:
        from neuronx_distributed_inference_tpu.utils.profiling import profile_fn

        def profiled():
            out = dispatch()
            jax.device_get(out.tokens)

        summary = profile_fn(profiled, profile_dir, n_warmup=1, n_profile=2)
        ops = (summary.get("ops") or [])[:12]
        total_us = summary.get("total_us")
        if total_us:
            device_s = total_us / 1e6 / 2  # n_profile=2 runs in the trace
    fl = prefill_flops(hf, S)
    res = {
        "S": S,
        "wall_ms": round(wall * 1e3, 2),
        "wall_tok_s": round(S / wall, 1),
        "mfu_wall": round(fl / wall / V5E_BF16_PEAK, 4),
    }
    if device_s:
        res["device_ms"] = round(device_s * 1e3, 2)
        res["mfu_device"] = round(fl / device_s / V5E_BF16_PEAK, 4)
    if ops:
        res["top_ops"] = ops[:6]
    return res


def flash_tile_candidates(shape_class="plain", dtype="bfloat16"):
    """The sweepable (bq, bkv) candidates, from the kernel audit's
    :func:`legal_tiles` — the SAME KERN701/702 arithmetic the gate runs, so
    the sweep and the gate can never disagree about what is sweepable."""
    from neuronx_distributed_inference_tpu.analysis.kernel_audit import legal_tiles

    return [(t["bq"], t["bkv"]) for t in
            legal_tiles("flash_attention", shape_class, dtype)]


def sweep_flash_blocks(S, D=64, H=32, dtype="bfloat16", n=10, packed=False,
                       softmax_bf16=None):
    """Standalone flash-kernel timing across the LEGAL tile sizes at the 1B
    attention shape — the actual tuning surface (candidates come from
    ``legal_tiles``; anything VMEM-over-budget or Mosaic-illegal is never
    timed). ``packed`` sweeps the head-pair packed kernel (round 6): the
    same (bq, bkv) grid at the new arithmetic intensity — packing halves
    head-grid steps and doubles per-tile lanes, so the winning tile must be
    re-measured, not assumed. ``softmax_bf16`` pins the packed softmax mode:
    sweep BOTH, because the shipping default (attention_softmax_fp32=True)
    runs fp32 exp/PV and its winning tile can differ from the bf16 mix."""
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.ops.flash_attention import (
        flash_attention_bhsd,
    )

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, H, S, D), jnp.bfloat16)
    kv_valid = jnp.ones((1, S), jnp.int32)
    rows = {}
    flops = 4 * S * S * H * D * 0.5
    for bq, bkv in flash_tile_candidates("plain", dtype):
        if bq > S or bkv > S:
            continue
        try:
            out, _, _ = flash_attention_bhsd(
                q, q, q, kv_valid, scale=D**-0.5, causal=True,
                bq=bq, bkv=bkv, packed=packed, softmax_bf16=softmax_bf16,
            )
            jax.device_get(out[0, 0, 0])
            # burst: dispatch n, fetch once — a per-iteration fetch pays
            # one relay RTT per call and swamps the kernel time
            t0 = time.time()
            for _ in range(n):
                out, _, _ = flash_attention_bhsd(
                    out, q, q, kv_valid, scale=D**-0.5, causal=True,
                    bq=bq, bkv=bkv, packed=packed, softmax_bf16=softmax_bf16,
                )
            jax.device_get(out[0, 0, 0])
            dt = (time.time() - t0) / n
            rows[f"bq{bq}_bkv{bkv}"] = {
                "ms": round(dt * 1e3, 2),
                "mfu": round(flops / dt / V5E_BF16_PEAK, 4),
            }
        except Exception as e:  # a tiling the backend rejects
            rows[f"bq{bq}_bkv{bkv}"] = {"error": str(e)[:80]}
    return rows


def run(tiny=False, profile=False):
    import bench

    if tiny:
        hf = dict(bench.TINY)
        lengths = (32, 64)
        seq = 64
        ce = [32, 64]
    else:
        hf = dict(bench.LLAMA_1B)
        lengths = (512, 2048, 8192)
        seq = 8192
        ce = [512, 2048, 8192]
    app = bench.build_app(
        hf, batch=1, seq_len=seq, ce_buckets=ce, tkg_buckets=[seq],
        quantized=False,
    )
    out = {"cte": []}
    for S in lengths:
        pdir = f"/tmp/prefill_prof_{S}" if profile else None
        out["cte"].append(measure_cte(app, S, hf, profile_dir=pdir))
    del app
    if not tiny:
        # unpacked vs head-packed at every tile: the packed winner becomes
        # the default, the unpacked column quantifies the packing win itself
        out["flash_sweep_8k"] = sweep_flash_blocks(8192)
        # packed in BOTH softmax modes: fp32 is the shipping default
        # (attention_softmax_fp32=True); bf16 is the opt-in fast mix — each
        # gets its own winning tile
        out["flash_sweep_8k_packed_fp32"] = sweep_flash_blocks(
            8192, packed=True, softmax_bf16=False
        )
        out["flash_sweep_8k_packed_bf16"] = sweep_flash_blocks(
            8192, packed=True, softmax_bf16=True
        )
    return out


def main():
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
    res = run(tiny="--tiny" in sys.argv, profile="--profile" in sys.argv)
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
