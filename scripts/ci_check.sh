#!/usr/bin/env bash
# The pre-PR check: the FULL static-analysis gate (tpulint + flag audit +
# graph/shard/memory audits + the roofline cost audit COST501-504 + the
# concurrency audit CONC601-604 + the kernel-contract audit KERN701-705 +
# the lifecycle audit LIFE801-805) plus the static_analysis pytest subset,
# as one command with a nonzero exit on ANY finding or test failure.
#
#   bash scripts/ci_check.sh            # text reports
#   bash scripts/ci_check.sh --json     # gate report as JSON
#
# Everything runs on a CPU-only host: the traced audits build tiny
# tp-sharded models on 8 virtual devices (the same GSPMD path hardware
# takes). After an INTENTIONAL contract change, regenerate baselines with
#   python scripts/run_static_analysis.py --write-baseline
# review the printed unified diff, and commit the *.json next to the code.
set -euo pipefail

cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
case "${XLA_FLAGS:-}" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" ;;
esac

rc=0

echo "== static-analysis gate (lint, flags, graph, shard, memory, cost, conc, kernel, life) =="
python scripts/run_static_analysis.py "$@" || rc=$?

echo
echo "== static_analysis pytest subset =="
python -m pytest tests -q -m static_analysis -p no:cacheprovider || rc=$?

echo
echo "== robustness (serving fault-containment) pytest subset =="
python -m pytest tests -q -m robustness -p no:cacheprovider || rc=$?

echo
echo "== router (multi-replica front-end + threaded stepping + disaggregated prefill tier + elastic add/retire) pytest subset =="
python -m pytest tests/test_router.py tests/test_router_threaded.py tests/test_disagg_router.py tests/test_elastic_router.py -q -m 'not slow' -p no:cacheprovider || rc=$?

echo
echo "== workload (open-loop traffic + SLO goodput) pytest subset =="
python -m pytest tests/test_workload.py -q -m 'not slow' -p no:cacheprovider || rc=$?

echo
echo "== kernel-contract (KERN701-705 detectors + tuning-table pins) pytest subset =="
python -m pytest tests/test_kernel_audit.py -q -m 'not slow' -p no:cacheprovider || rc=$?

echo
echo "== lifecycle audit (LIFE801-805 detectors + elastic licensing) pytest subset =="
python -m pytest tests/test_lifecycle_audit.py -q -m 'not slow' -p no:cacheprovider || rc=$?

echo
echo "== observability (span timelines + ops server + SLO burn-rate) pytest subset =="
python -m pytest tests/test_telemetry.py tests/test_obs_timeline.py tests/test_ops_server.py -q -m 'not slow' -p no:cacheprovider || rc=$?

if [ "$rc" -ne 0 ]; then
  echo "ci_check: FAILED (rc=$rc)" >&2
else
  echo "ci_check: OK"
fi
exit "$rc"
