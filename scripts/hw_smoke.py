#!/usr/bin/env python
"""One-command hardware smoke suite: the real-TPU validations the CPU test
suite cannot perform (it runs kernels in interpret mode on a virtual mesh).

Run on a host with a real TPU chip: ``python scripts/hw_smoke.py [--fast]``.
Each check prints PASS/FAIL; exit code 0 iff all pass. Covers the round-4
hardware findings so future rounds re-verify them in minutes instead of
rediscovering them:

1. flash prefill kernel at batch 4 (the r3 Mosaic regression shape)
2. HF greedy-token parity end-to-end (fp32)
3. fused decode-layer kernels vs native (bf16 logit tolerance)
4. fused selected-experts MoE decode vs dense
5. multimodal (llava image-to-text) exact HF tokens, fp32 + bf16
6. disaggregated prefill/decode token parity
7. speculative serving == plain serving tokens
8. 8k-context prefill + decode (long-sequence kernel shapes; skipped --fast)
"""

from __future__ import annotations

import os
import sys
import traceback

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)  # the repo package, wherever this is invoked from
sys.path.insert(0, os.path.join(_ROOT, "tests"))  # test helpers

RESULTS = []


def check(name):
    def deco(fn):
        RESULTS.append((name, fn))
        return fn

    return deco


def _tiny_cfg(**tpu):
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig

    hf = dict(
        model_type="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, num_hidden_layers=2,
        vocab_size=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        max_position_embeddings=256, hidden_act="silu", tie_word_embeddings=False,
    )
    kw = dict(batch_size=2, seq_len=64, dtype="float32")
    kw.update(tpu)
    return LlamaInferenceConfig(
        TpuConfig(**kw), load_config=lambda c: [setattr(c, k, v) for k, v in hf.items()]
    )


def _rand_sd(cfg, seed=0):
    from conftest import make_random_hf_state_dict

    return make_random_hf_state_dict(cfg, seed=seed)


@check("flash prefill kernel at batch 4 (r3 regression shape)")
def _flash_b4():
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.ops.flash_attention import (
        flash_attention_bhsd,
    )

    B, H, S, D = 4, 8, 256, 64
    q = jnp.ones((B, H, S, D), jnp.bfloat16)
    kv = jnp.ones((B, S), jnp.int32)
    out = flash_attention_bhsd(q, q, q, kv, scale=0.125, causal=True, interpret=False)
    assert np.isfinite(np.asarray(out[0], np.float32)).all()


@check("HF greedy-token parity end-to-end (fp32)")
def _hf_parity():
    import torch
    import transformers

    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attn_implementation="eager",
        eos_token_id=None, bos_token_id=None,
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval().float()
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    prompt = np.array([[5, 17, 92, 41, 33, 88, 2, 11]])
    with torch.no_grad():
        ref = hf.generate(
            torch.tensor(prompt), max_new_tokens=12, do_sample=False, pad_token_id=0
        ).numpy()
    cfg = _tiny_cfg(batch_size=1)
    for k, v in hf_cfg.to_dict().items():
        setattr(cfg, k, v)
    app = TpuModelForCausalLM(None, cfg).load(state_dict=sd)
    out = app.generate(prompt, np.ones_like(prompt), max_new_tokens=12)
    assert (out.sequences == ref).all()


@check("fused decode-layer kernels vs native (bf16)")
def _fused_layers():
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    sd = None
    logits = {}
    for fused in (False, True):
        cfg = _tiny_cfg(
            dtype="bfloat16", fused_qkv=True, seq_len=1024,
            fused_attn_block_kernel_enabled=fused, fused_mlp_kernel_enabled=fused,
            token_generation_buckets=[512], output_logits=True,
        )
        if sd is None:
            sd = _rand_sd(cfg)
        app = TpuModelForCausalLM(None, cfg).load(state_dict=sd)
        ids = np.array([[1, 2, 3, 4], [5, 6, 7, 8]])
        logits[fused] = app.generate(ids, np.ones_like(ids), max_new_tokens=6).logits
    d = np.abs(logits[True] - logits[False]).max()
    scale = np.abs(logits[False]).max()
    assert d <= 0.05 * scale, f"fused/native logit gap {d} vs scale {scale}"


@check("fused selected-experts MoE decode vs dense")
def _fused_moe():
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.modules.moe import (
        MoESpec,
        expert_mlps_dense,
        router_top_k,
    )
    from neuronx_distributed_inference_tpu.ops.moe_decode import fused_moe_decode

    rng = np.random.RandomState(0)
    E, k, H, I = 8, 2, 256, 512
    spec = MoESpec(num_experts=E, top_k=k)
    params = {
        n: {"weight": jnp.asarray(rng.randn(E, *s).astype(np.float32) * 0.05, jnp.bfloat16)}
        for n, s in (("gate_proj", (H, I)), ("up_proj", (H, I)), ("down_proj", (I, H)))
    }
    x = jnp.asarray(rng.randn(1, H).astype(np.float32), jnp.bfloat16)
    aff, sel = router_top_k(jnp.asarray(rng.randn(1, E).astype(np.float32)), spec)
    ref = expert_mlps_dense(params, x, aff, spec, sel)
    w_topk, e_topk = jax.lax.top_k(aff, k)
    out = fused_moe_decode(
        x, e_topk.astype(jnp.int32), w_topk,
        params["gate_proj"]["weight"], params["up_proj"]["weight"],
        params["down_proj"]["weight"], act="silu", interpret=False,
    )
    d = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
    assert d < 0.05, f"moe kernel divergence {d}"


@check("multimodal (llava) exact HF tokens, fp32 + bf16")
def _multimodal():
    import torch

    from test_multimodal import _tiny_hf_llava
    from neuronx_distributed_inference_tpu.config import InferenceConfig, TpuConfig
    from neuronx_distributed_inference_tpu.runtime.image_to_text import (
        TpuImageToTextModel,
    )

    hf = _tiny_hf_llava()
    sd = {k: v.float().numpy() for k, v in hf.state_dict().items()}

    def load_config(cfg):
        for k, v in hf.config.to_dict().items():
            setattr(cfg, k, v)

    ids = np.array([[1] + [99] * 16 + [5, 17, 9]])
    mask = np.ones_like(ids)
    px = np.random.RandomState(1).randn(1, 3, 64, 64).astype(np.float32)
    with torch.no_grad():
        ref = hf.generate(
            input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask),
            pixel_values=torch.tensor(px), max_new_tokens=8, do_sample=False,
            pad_token_id=0,
        ).numpy()
    for dt in ("float32", "bfloat16"):
        cfg = InferenceConfig(
            TpuConfig(batch_size=1, seq_len=64, dtype=dt), load_config=load_config
        )
        app = TpuImageToTextModel(None, cfg)
        app.load(state_dict=sd)
        out = app.generate(ids, mask, pixel_values=px, max_new_tokens=8)
        assert (out.sequences == ref).all(), dt


@check("disaggregated prefill/decode token parity")
def _disagg():
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )
    from neuronx_distributed_inference_tpu.runtime.disaggregated import (
        DisaggregatedPipeline,
    )

    sd = None
    apps = {}
    for name, stage in (("mono", None), ("pre", True), ("dec", False)):
        cfg = _tiny_cfg(is_prefill_stage=stage)
        if sd is None:
            sd = _rand_sd(cfg)
        apps[name] = TpuModelForCausalLM(None, cfg).load(state_dict=sd)
    ids = np.array([[5, 17, 92, 41], [64, 3, 27, 9]])
    mask = np.ones_like(ids)
    ref = apps["mono"].generate(ids, mask, max_new_tokens=10).sequences
    out = DisaggregatedPipeline(apps["pre"], apps["dec"]).generate(
        ids, mask, max_new_tokens=10
    ).sequences
    assert (out == ref).all()


@check("speculative serving == plain serving tokens")
def _spec_serving():
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )
    from neuronx_distributed_inference_tpu.runtime.serving import (
        ServingSession,
        SpeculativeServingSession,
    )

    mk = lambda: _tiny_cfg(is_continuous_batching=True, ctx_batch_size=1)
    sd = _rand_sd(mk())
    plain = TpuModelForCausalLM(None, mk()).load(state_dict=sd)
    sess_p = ServingSession(plain)
    sess_p.add_request("r", [5, 17, 92, 41], max_new_tokens=10)
    golden = sess_p.run_to_completion()["r"]
    target = TpuModelForCausalLM(None, mk()).load(state_dict=sd)
    draft = TpuModelForCausalLM(None, mk()).load(state_dict=_rand_sd(mk(), seed=3))
    sess = SpeculativeServingSession(target, draft, speculation_length=4)
    sess.add_request("r", [5, 17, 92, 41], max_new_tokens=10)
    assert sess.run_to_completion()["r"] == golden


@check("8k-context prefill + decode (long-sequence shapes)")
def _long_ctx():
    if "--fast" in sys.argv:
        return
    import bench as B
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    attrs = dict(B.LLAMA_1B, max_position_embeddings=16384)
    tc = TpuConfig(
        batch_size=1, seq_len=8704, dtype="bfloat16", fused_qkv=True,
        enable_bucketing=True, context_encoding_buckets=[8192],
        token_generation_buckets=[8704],
    )
    app = TpuModelForCausalLM(
        None,
        LlamaInferenceConfig(tc, load_config=lambda c: [setattr(c, k, v) for k, v in attrs.items()]),
    )
    app.load(random_weights=True)
    ids = np.random.RandomState(0).randint(0, 120000, size=(1, 8192))
    out = app.generate(ids, np.ones_like(ids), max_new_tokens=16)
    assert out.sequences.shape == (1, 8208)


def main():
    import jax

    print(f"devices: {jax.devices()}", file=sys.stderr)
    failed = 0
    for name, fn in RESULTS:
        try:
            fn()
            print(f"PASS  {name}")
        except Exception:
            failed += 1
            print(f"FAIL  {name}")
            traceback.print_exc()
    print(f"\n{len(RESULTS) - failed}/{len(RESULTS)} hardware checks passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
