#!/usr/bin/env python
"""Speculation machinery benchmark: measured tok/s for the speculative
decoding modes on real hardware (VERDICT r4 next #6 — "measured, not just
bounded"; reference fused-spec decode step model_base.py:2562-3021).

No trained draft weights exist in this environment, so the harness builds
drafts whose acceptance is a PROPERTY OF CONSTRUCTION:

- ``assisted_self``: vanilla assisted decoding with the draft = a second
  app holding the SAME weights as the target (self-draft). Greedy
  verification then accepts every proposal, so the measured tok/s isolates
  the machinery (draft chain + multi-token verify + host accept loop) at
  acceptance = 100% — directly comparable to the r4 verify-ceiling
  microbench (PERF.md: k=4 => 720 tok/s ceiling with a FREE draft; here the
  draft costs k-1 full target steps, so the self-draft ideal is ~= plain
  decode; the gap to that ideal is the machinery overhead).
- ``eagle_chain`` / ``eagle_tree``: fused EAGLE speculation with a
  CORRELATED 1-layer draft (shared embed/lm-head/final-norm, target layer 0,
  pass-through fusion) — a real feature-chained draft with nontrivial
  acceptance on a random-weight target; tok/s is reported TOGETHER with the
  measured acceptance (tokens/round) so the machinery cost per round is
  separable from draft quality.
- ``plain``: the no-speculation baseline on the same weights.

Every mode is size-parameterized and smoke-run by the CPU suite
(tests/test_spec_bench_smoke.py) — bench-only crash classes must stay
impossible (VERDICT r3 weak #2).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def _sizes(tiny):
    if tiny:
        return dict(
            hf=dict(
                model_type="llama", hidden_size=64, intermediate_size=128,
                num_attention_heads=4, num_key_value_heads=2,
                num_hidden_layers=2, vocab_size=128, rms_norm_eps=1e-5,
                rope_theta=1e4, max_position_embeddings=256,
                hidden_act="silu", tie_word_embeddings=False,
            ),
            seq=128, prompt=8, gen=16, k=4,
        )
    import bench

    return dict(hf=dict(bench.LLAMA_1B), seq=1024, prompt=128, gen=256, k=4)


def _mk_config(hf, seq, tpu_kwargs):
    from neuronx_distributed_inference_tpu.config import TpuConfig
    from neuronx_distributed_inference_tpu.models.llama import LlamaInferenceConfig

    def load_cfg(c):
        for k, v in hf.items():
            setattr(c, k, v)

    tc = TpuConfig(batch_size=1, seq_len=seq, dtype="bfloat16", **tpu_kwargs)
    return LlamaInferenceConfig(tc, load_config=load_cfg)


def _plain_app(hf, seq, **tpu_kwargs):
    from neuronx_distributed_inference_tpu.runtime.application import (
        TpuModelForCausalLM,
    )

    cfg = _mk_config(hf, seq, tpu_kwargs)
    return TpuModelForCausalLM(None, cfg).load(random_weights=True)


def _eagle_app(hf, seq, k, tree=None):
    """Fused EAGLE app with a correlated 1-layer draft: the draft shares the
    target's embedding/lm-head/final-norm, copies target layer 0, and uses a
    pass-through fusion layer — feature-chained speculation with measurable
    acceptance on a random-weight target (the construction
    tests/test_token_tree.py's acceptance test pins)."""
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.config import FusedSpecConfig
    from neuronx_distributed_inference_tpu.parallel.sharding import shard_pytree
    from neuronx_distributed_inference_tpu.runtime.fused_spec import (
        TpuEagleSpecModelForCausalLM,
    )

    cfg = _mk_config(
        hf, seq,
        dict(
            speculation_length=k,
            enable_fused_speculation=True,
            enable_eagle_speculation=True,
            token_tree_config=tree,
        ),
    )
    draft_hf = dict(hf, num_hidden_layers=1, model_type="llama-eagle")
    draft_cfg = _mk_config(draft_hf, seq, {})
    cfg.fused_spec_config = FusedSpecConfig(
        draft_model_name="self-1l", draft_config=draft_cfg
    )
    app = TpuEagleSpecModelForCausalLM(None, cfg)
    app.load(random_weights=True)

    t = jax.device_get(app.target_params)
    d = app.draft_builder.random_params(on_host=False)
    H = cfg.hidden_size
    fc = np.zeros((2 * H, H), np.float32)
    fc[H:, :] = np.eye(H)
    d["fc"]["weight"] = jnp.asarray(fc, jnp.bfloat16)
    for name in ("embed_tokens", "lm_head", "norm"):
        if name in t:
            d[name] = t[name]
    d["layers"] = jax.tree.map(lambda x: x[:1], t["layers"])
    app.draft_params = shard_pytree(
        d, app.draft_builder.param_pspecs(), app.mesh
    )
    return app


def burst_round_ms(app, R=24):
    """Pure DEVICE cost of one fused speculation round: dispatch R rounds
    back-to-back on fixed inputs (caches donate-thread through _call_tkg)
    and block once at the end. On a tunneled chip the end-to-end loop pays
    a host RTT per round that says nothing about the machinery — this is
    the number that transfers to locally-attached hardware."""
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.models.base import StepInputs
    from neuronx_distributed_inference_tpu.modules.sampling import (
        prepare_sampling_params,
    )

    ids = np.array([[5, 7, 11, 13]])
    app.generate(ids, np.ones_like(ids), max_new_tokens=8)  # compile + seed state
    B = 1
    bucket = app.tkg_buckets[-1]
    inputs = StepInputs(
        input_ids=jnp.asarray([[17]], jnp.int32),
        attention_mask=jnp.zeros((B, bucket), jnp.int32),
        position_ids=jnp.asarray([[bucket // 2]], jnp.int32),
        seq_ids=jnp.asarray(np.arange(B, dtype=np.int32)),
        sampling_params=jnp.asarray(prepare_sampling_params(B), jnp.float32),
    )
    out = app._call_tkg(inputs, None)
    jax.block_until_ready(out.tokens)
    t0 = time.time()
    for _ in range(R):
        out = app._call_tkg(inputs, None)
    jax.block_until_ready(out.tokens)
    return (time.time() - t0) / R * 1e3


def _measure_generate(app, prompt, gen, count_rounds=False):
    ids = np.asarray(prompt)[None, :]
    mask = np.ones_like(ids)
    app.generate(ids, mask, max_new_tokens=gen)  # compile/warm
    rounds = [0]
    if count_rounds:
        orig = app._call_tkg

        def counting(inputs, key):
            rounds[0] += 1
            return orig(inputs, key)

        app._call_tkg = counting
    # no cache reset needed: prefill rewrites from position 0 and the masks
    # bound every read to the live positions
    t0 = time.time()
    out = app.generate(ids, mask, max_new_tokens=gen)
    dt = time.time() - t0
    if count_rounds:
        app._call_tkg = orig
    return out.num_generated / dt, out.num_generated, rounds[0]


def run(tiny=False):
    s = _sizes(tiny)
    hf, seq, prompt_len, gen, k = s["hf"], s["seq"], s["prompt"], s["gen"], s["k"]
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, hf["vocab_size"] - 10, size=prompt_len).tolist()
    res = {}

    # plain decode baseline
    app = _plain_app(hf, seq)
    tok_s, _, _ = _measure_generate(app, prompt, gen)
    res["plain_tok_s"] = round(tok_s, 2)
    del app

    # vanilla assisted, self-draft (acceptance == 1 by construction)
    from neuronx_distributed_inference_tpu.runtime.assisted import assisted_generate

    target = _plain_app(hf, seq)
    draft = _plain_app(hf, seq)  # same seed -> identical weights
    ids = np.asarray(prompt)[None, :]
    mask = np.ones_like(ids)
    assisted_generate(target, draft, ids, mask, max_new_tokens=gen,
                      speculation_length=k)  # compile/warm
    target.init_kv_cache()
    draft.init_kv_cache()
    t0 = time.time()
    out = assisted_generate(target, draft, ids, mask, max_new_tokens=gen,
                            speculation_length=k)
    dt = time.time() - t0
    res["assisted_self_tok_s"] = round(out.num_generated / dt, 2)
    res["assisted_k"] = k
    del target, draft

    # fused EAGLE chain + static tree with the correlated draft
    for name, tree in (
        ("eagle_chain", None),
        ("eagle_tree", {0: [1, 2], 1: [3, 4]}),
    ):
        app = _eagle_app(hf, seq, k, tree=tree)
        tok_s, n_gen, rounds = _measure_generate(
            app, prompt, gen, count_rounds=True
        )
        res[f"{name}_tok_s"] = round(tok_s, 2)
        res[f"{name}_tokens_per_round"] = round(n_gen / max(rounds, 1), 2)
        res[f"{name}_round_ms_device"] = round(burst_round_ms(app), 2)
        del app

    return res


def main():
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
    tiny = "--tiny" in sys.argv
    res = run(tiny=tiny)
    import jax

    res["device"] = str(jax.devices()[0])
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
