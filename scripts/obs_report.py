#!/usr/bin/env python
"""Post-mortem observability report: one annotated join of a run's
telemetry artifacts (ISSUE 19 — the artifact hardware session zero
attaches to every BENCH row).

    python scripts/obs_report.py --events run.jsonl
    python scripts/obs_report.py --events run.jsonl --metrics metrics.json \
        --top 5 --bucket-steps 4

Inputs:

- ``--events``  : a TelemetrySession JSONL event log (``jsonl_path=`` /
  ``enable_default_session``), the primary source — request lifecycle,
  ``workload_step`` commit totals, ``chaos_kill`` markers, ``handoff_done``
  taxes and ``slo_missed`` verdicts are all read from it.
- ``--metrics`` : optional ``--metrics-out`` snapshot JSON; appends the
  grouped metric table (scripts/metrics_report.py render).

Sections: goodput timeline (per-bucket committed tokens with chaos kills
and the measured recovery window marked via workload/slo.extract_dip),
hand-off TTFT-tax distribution, per-tenant SLO attainment, and the top-N
slowest requests by TTFT with their span breakdown (queue -> prefill/
hand-off -> decode, failover count).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional


def _percentile(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    k = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[k]


def _base_id(rid: str) -> str:
    import re

    return re.sub(r"~f\d+$", "", rid)


def _tenant_of(rid: str) -> str:
    base = _base_id(rid)
    return base.rsplit("-", 1)[0] if "-" in base else "default"


class RunJoin:
    """The joined view of one run's JSONL event log."""

    def __init__(self, events: List[dict]):
        self.events = events
        self.step_commits: Dict[int, int] = {}
        self.kills: List[dict] = []
        self.handoff_ms: List[float] = []
        self.misses: Dict[str, Dict[str, int]] = {}  # tenant -> kind -> n
        self.reqs: Dict[str, dict] = {}
        for ev in events:
            et = ev.get("type")
            rid = ev.get("req_id")
            base = _base_id(rid) if rid else None
            if et == "workload_step":
                self.step_commits[int(ev["step"])] = int(ev["commit_tokens"])
            elif et == "chaos_kill":
                self.kills.append(ev)
            elif et == "handoff_done":
                self.handoff_ms.append(float(ev["ms"]))
                if base:
                    r = self._req(base)
                    r["handoff_ms"] = r.get("handoff_ms", 0.0) + float(ev["ms"])
            elif et == "slo_missed":
                t = self.misses.setdefault(ev.get("tenant", "default"), {})
                t[ev["kind"]] = t.get(ev["kind"], 0) + 1
            elif et == "request_submitted":
                r = self._req(base)
                r.setdefault("t_submit", ev["ts"])
                r["incarnations"] = r.get("incarnations", 0) + 1
            elif et == "first_token":
                r = self._req(base)
                if "t_first" not in r:
                    r["t_first"] = ev["ts"]
            elif et == "router_failover":
                self._req(base)["failovers"] = (
                    self._req(base).get("failovers", 0) + 1
                )
            elif et in ("request_finished", "request_dropped",
                        "request_rejected"):
                r = self._req(base)
                r["t_end"] = ev["ts"]
                r["end"] = ev.get("reason", et)

    def _req(self, base: str) -> dict:
        return self.reqs.setdefault(base, {})


def render_goodput_timeline(join: RunJoin, bucket_steps: int) -> List[str]:
    out = ["goodput timeline (committed tokens per bucket):"]
    if not join.step_commits:
        out.append("  (no workload_step events — not an open-loop run)")
        return out
    last = max(join.step_commits)
    series: List[int] = []
    for b0 in range(0, last + 1, bucket_steps):
        series.append(sum(
            join.step_commits.get(s, 0)
            for s in range(b0, min(b0 + bucket_steps, last + 1))
        ))
    kill_steps = [int(k["step"]) for k in join.kills]
    dip = None
    if kill_steps:
        try:
            from neuronx_distributed_inference_tpu.workload.slo import (
                extract_dip,
            )

            dip = extract_dip(
                series, kill_steps[0] // bucket_steps,
                bucket_steps=bucket_steps,
            )
        except Exception:
            dip = None
    peak = max(series) if series else 1
    recov_bucket = None
    if dip is not None and dip.recovery_steps is not None:
        recov_bucket = (
            kill_steps[0] // bucket_steps
            + dip.recovery_steps // bucket_steps
        )
    for i, v in enumerate(series):
        bar = "#" * int(round(24 * v / peak)) if peak else ""
        marks = []
        for ks in kill_steps:
            if ks // bucket_steps == i:
                marks.append("<- CHAOS KILL")
        if recov_bucket is not None and i == recov_bucket:
            marks.append("<- recovered")
        out.append(
            f"  step {i * bucket_steps:>4}  {v:>6} {bar:<24} "
            f"{' '.join(marks)}".rstrip()
        )
    if dip is not None:
        out.append(
            f"  dip_frac={dip.dip_frac} recovery_steps={dip.recovery_steps} "
            f"(baseline {dip.baseline:.1f} tok/bucket)"
        )
    return out


def render_handoff_tax(join: RunJoin) -> List[str]:
    out = ["hand-off TTFT tax (nxdi_handoff_ms, per completed hand-off):"]
    hs = join.handoff_ms
    if not hs:
        out.append("  (no hand-offs — no disaggregated prefill tier)")
        return out
    out.append(
        f"  n={len(hs)} mean={sum(hs) / len(hs):.3f}ms "
        f"p50={_percentile(hs, .5):.3f}ms p95={_percentile(hs, .95):.3f}ms "
        f"max={max(hs):.3f}ms"
    )
    return out


def render_tenant_attainment(join: RunJoin) -> List[str]:
    out = ["per-tenant SLO attainment:"]
    by_tenant: Dict[str, int] = {}
    for base in join.reqs:
        by_tenant[_tenant_of(base)] = by_tenant.get(_tenant_of(base), 0) + 1
    if not by_tenant:
        out.append("  (no requests in the event log)")
        return out
    for tenant in sorted(by_tenant):
        n = by_tenant[tenant]
        misses = join.misses.get(tenant, {})
        n_miss = sum(misses.values())
        att = (n - n_miss) / n if n else 1.0
        detail = (
            " ".join(f"{k}={v}" for k, v in sorted(misses.items()))
            or "-"
        )
        out.append(
            f"  {tenant:<16} requests={n:<5} attainment={att:.4f} "
            f"misses: {detail}"
        )
    return out


def render_slowest(join: RunJoin, top: int) -> List[str]:
    out = [f"top-{top} slowest requests by TTFT (span breakdown):"]
    rows = []
    for base, r in join.reqs.items():
        if "t_submit" not in r or "t_first" not in r:
            continue
        ttft = r["t_first"] - r["t_submit"]
        decode = (
            r["t_end"] - r["t_first"] if "t_end" in r else None
        )
        rows.append((ttft, base, r, decode))
    if not rows:
        out.append("  (no served requests)")
        return out
    rows.sort(key=lambda x: (-x[0], x[1]))
    out.append(
        f"  {'request':<20} {'ttft_s':>9} {'handoff_ms':>11} "
        f"{'decode_s':>9} {'failovers':>9}  end"
    )
    for ttft, base, r, decode in rows[:top]:
        out.append(
            f"  {base:<20} {ttft:>9.3f} "
            f"{r.get('handoff_ms', 0.0):>11.3f} "
            f"{(f'{decode:.3f}' if decode is not None else '-'):>9} "
            f"{r.get('failovers', 0):>9}  {r.get('end', 'open')}"
        )
    return out


def render_report(events: List[dict], *, metrics: Optional[dict] = None,
                  bucket_steps: int = 4, top: int = 10) -> str:
    join = RunJoin(events)
    n_req = len(join.reqs)
    finished = sum(1 for r in join.reqs.values() if "t_end" in r)
    total_commits = sum(join.step_commits.values())
    out = [
        "== observability report ==",
        f"requests={n_req} terminal={finished} "
        f"workload_commit_tokens={total_commits} "
        f"chaos_kills={len(join.kills)} events={len(events)}",
        "",
    ]
    out.extend(render_goodput_timeline(join, bucket_steps))
    out.append("")
    out.extend(render_handoff_tax(join))
    out.append("")
    out.extend(render_tenant_attainment(join))
    out.append("")
    out.extend(render_slowest(join, top))
    if metrics is not None:
        from metrics_report import render as render_metrics

        out.append("")
        out.append("== metrics snapshot ==")
        out.append(render_metrics(metrics))
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--events", required=True,
                   help="TelemetrySession JSONL event log")
    p.add_argument("--metrics", default=None,
                   help="optional --metrics-out snapshot JSON to append")
    p.add_argument("--bucket-steps", type=int, default=4,
                   help="goodput timeline bucket width in driver steps")
    p.add_argument("--top", type=int, default=10,
                   help="slowest-request rows to show")
    args = p.parse_args(argv)
    from neuronx_distributed_inference_tpu.telemetry.tracing import (
        load_events,
    )

    events = load_events(args.events)
    metrics = None
    if args.metrics:
        with open(args.metrics) as f:
            metrics = json.load(f)
    print(render_report(events, metrics=metrics,
                        bucket_steps=args.bucket_steps, top=args.top))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    sys.exit(main())
