#!/usr/bin/env python
"""Batched-decode efficiency study + the promised fused-kernel revisit at
bs >= 4 (VERDICT r4 next #5: explain the bs=4 gap to the weights-read-once
ideal and re-measure ops/decode_block.py in the batch regime its r4
deferral named).

For bs in {1, 2, 4, 8}, bf16-1B decode (contiguous cache, tkg bucket 512):
- XLA-fused native path (the default) tok/s;
- fused decode-layer Pallas kernels FORCED on (attention block + MLP block,
  fused_attn_block_kernel_enabled=True/fused_mlp_kernel_enabled=True);
- the HBM roofline ideal: decode is weight-bandwidth-bound, so
  ideal step = (weight bytes + bs * kv bytes/step) / 819 GB/s and
  ideal tok/s = bs / step.

Run on hardware: python scripts/decode_scaling.py
CPU smoke:       python scripts/decode_scaling.py --tiny --cpu
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

HBM_GBS = 819e9  # v5e


def _weight_bytes(hf, dtype_bytes=2):
    """Per-STEP streamed weight bytes: layers + lm_head. The input embed
    table is a bs-row gather, not a stream — counting it would overstate
    the roofline for untied-embedding models."""
    H, I, L, V = (hf["hidden_size"], hf["intermediate_size"],
                  hf["num_hidden_layers"], hf["vocab_size"])
    Hq, Hkv = hf["num_attention_heads"], hf["num_key_value_heads"]
    D = hf.get("head_dim", H // Hq)
    per_layer = H * Hq * D + 2 * H * Hkv * D + Hq * D * H + 3 * H * I
    lm_head = V * H
    return (L * per_layer + lm_head) * dtype_bytes


def measure_bs(app, bs, hf, prompt_len=128, gen=256):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, hf["vocab_size"] - 10, size=(bs, prompt_len))
    mask = np.ones_like(ids)
    app.generate(ids, mask, max_new_tokens=gen)  # compile/warm
    t0 = time.time()
    out = app.generate(ids, mask, max_new_tokens=gen)
    dt = time.time() - t0
    return out.num_generated * bs / dt


def sweep_tkg_tiles(bucket=512, dtype="bfloat16", B=1, n=20):
    """Standalone TKG-decode kernel timing across the LEGAL kv-tile sizes
    (``bs``) at the 1B decode shape. Candidates come from the kernel
    audit's ``legal_tiles`` — the same KERN701/702 arithmetic the gate
    runs — so this sweep can only ever measure tilings the gate would
    accept, and its winner is what a hardware session promotes into
    ``analysis/tuning_table.json`` (provenance ``measured``)."""
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.analysis.kernel_audit import legal_tiles
    from neuronx_distributed_inference_tpu.ops.decode_attention import (
        tkg_decode_attention,
    )

    L, Hq, Hkv, D = 16, 32, 8, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, 1, Hq, D), jnp.bfloat16)
    cache = jnp.asarray(
        rng.randn(L, B, bucket, Hkv, D), jnp.dtype(dtype)
    )
    li = jnp.int32(0)
    mask = jnp.ones((B, 1, 1, bucket), bool)
    rows = {}
    for tiles in legal_tiles("tkg_decode_attention", f"kv{bucket}", dtype):
        bs = tiles["bs"]
        try:
            out = tkg_decode_attention(
                q, cache, cache, li, mask, scale=D**-0.5, n_kv=Hkv, bs=bs
            )
            jax.device_get(out[0, 0, 0, 0])
            t0 = time.time()
            for _ in range(n):
                out = tkg_decode_attention(
                    q, cache, cache, li, mask, scale=D**-0.5, n_kv=Hkv, bs=bs
                )
            jax.device_get(out[0, 0, 0, 0])
            rows[f"bs{bs}"] = {"us": round((time.time() - t0) / n * 1e6, 1)}
        except Exception as e:  # a tiling the backend rejects
            rows[f"bs{bs}"] = {"error": str(e)[:80]}
    return rows


def sweep_quant_matmul_tiles(shape_class="k2048_n8192", B=8, n=20,
                             interpret=False):
    """Standalone int4 fused-dequant matmul timing across the LEGAL output
    tiles (``bn``) at a committed registry shape (ISSUE 17). Same contract
    as :func:`sweep_tkg_tiles`: candidates come from the kernel audit's
    ``legal_tiles`` so only gate-acceptable tilings are measured, and a
    hardware winner is what gets promoted into
    ``analysis/tuning_table.json`` (provenance ``measured``)."""
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_inference_tpu.analysis.kernel_audit import legal_tiles
    from neuronx_distributed_inference_tpu.ops.quant_matmul import (
        quant_matmul,
        quantize_tensor_int4,
    )

    K, N = (int(p[1:]) for p in shape_class.split("_"))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, K), jnp.bfloat16)
    packed = quantize_tensor_int4(rng.randn(K, N).astype(np.float32))
    w = jnp.asarray(packed["weight"])
    s = jnp.asarray(packed["scale"])
    rows = {}
    for tiles in legal_tiles("quant_matmul", shape_class, "bfloat16"):
        bn = tiles["bn"]
        try:
            out = quant_matmul(x, w, s, bn=bn, interpret=interpret)
            jax.device_get(out[0, 0])
            t0 = time.time()
            for _ in range(n):
                out = quant_matmul(x, w, s, bn=bn, interpret=interpret)
            jax.device_get(out[0, 0])
            rows[f"bn{bn}"] = {"us": round((time.time() - t0) / n * 1e6, 1)}
        except Exception as e:  # a tiling the backend rejects
            rows[f"bn{bn}"] = {"error": str(e)[:80]}
    return rows


def run(tiny=False):
    import bench

    hf = dict(bench.TINY if tiny else bench.LLAMA_1B)
    seq = 64 if tiny else 1024
    ce = [16] if tiny else [128]
    tkg = [64] if tiny else [512]
    prompt, gen = (8, 8) if tiny else (128, 256)
    wb = _weight_bytes(hf)
    out = {"weight_gb": round(wb / 1e9, 2), "rows": []}
    for bs in (1, 2, 4, 8):
        row = {"bs": bs}
        for name, extra in (
            ("xla", {}),
            ("fused_blocks", dict(
                fused_attn_block_kernel_enabled=True,
                fused_mlp_kernel_enabled=True,
            )),
        ):
            app = bench.build_app(
                hf, batch=bs, seq_len=seq, ce_buckets=ce, tkg_buckets=tkg,
                quantized=False,
                cache_key=(None if tiny else "bf16_1b"),
                extra_tpu=extra,
            )
            row[f"{name}_tok_s"] = round(measure_bs(app, bs, hf, prompt, gen), 1)
            del app
        # per-step KV traffic: read bs * pos * Hkv * D * 2 streams * 2B —
        # use the midpoint position of the measured run
        Hkv = hf["num_key_value_heads"]
        D = hf.get("head_dim", hf["hidden_size"] // hf["num_attention_heads"])
        kv = bs * (prompt + gen / 2) * Hkv * D * 2 * 2
        ideal_step = (wb + kv) / HBM_GBS
        row["roofline_tok_s"] = round(bs / ideal_step, 1)
        row["xla_pct_of_roofline"] = round(
            100 * row["xla_tok_s"] / row["roofline_tok_s"], 1
        )
        out["rows"].append(row)
    if not tiny:
        # kernel-level kv-tile sweep over the gate-legal candidates only
        out["tkg_tile_sweep_kv512"] = sweep_tkg_tiles(bucket=512)
        # int4 quant-matmul output-tile sweep at the committed 1B decode
        # shape (ISSUE 17) — same legal_tiles-sourced candidate contract
        out["quant_matmul_tile_sweep_1b"] = sweep_quant_matmul_tiles()
    return out


def main():
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
    res = run(tiny="--tiny" in sys.argv)
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
