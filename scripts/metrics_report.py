#!/usr/bin/env python
"""Pretty-print a telemetry metrics snapshot (``--metrics-out`` JSON).

    python scripts/metrics_report.py metrics.json
    python scripts/metrics_report.py metrics.json --prometheus   # raw text

Stdlib-only on purpose: the snapshot format is the JSON side of the
exposition contract (docs/OBSERVABILITY.md), and this script is its
reference consumer — ``render()`` is imported by the test suite so the
format cannot drift silently.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _labels_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def _hist_quantile(buckets: Dict[str, int], count: int, q: float):
    """Bucket-resolution quantile from a cumulative {le: count} map."""
    if not count:
        return None
    rank = q * count
    for le, c in buckets.items():
        if c >= rank:
            return le
    return "+Inf"


def render(snapshot: Dict) -> str:
    """One aligned table per metric kind from a registry snapshot dict."""
    counters: List[str] = []
    gauges: List[str] = []
    hists: List[str] = []
    for name, fam in sorted(snapshot.items()):
        kind = fam.get("type")
        for s in fam.get("samples", []):
            label = f"{name}{_labels_str(s.get('labels', {}))}"
            if kind == "counter":
                counters.append(f"  {label:<64} {s['value']:>14g}")
            elif kind == "gauge":
                gauges.append(f"  {label:<64} {s['value']:>14g}")
            elif kind == "histogram":
                count = s["count"]
                mean = (s["sum"] / count) if count else 0.0
                p50 = _hist_quantile(s["buckets"], count, 0.50)
                p99 = _hist_quantile(s["buckets"], count, 0.99)
                hists.append(
                    f"  {label:<52} n={count:<8} sum={s['sum']:<12.6g} "
                    f"mean={mean:<10.4g} p50<={p50} p99<={p99}"
                )
    out = []
    if counters:
        out.append("counters:")
        out.extend(counters)
    if gauges:
        out.append("gauges:")
        out.extend(gauges)
    if hists:
        out.append("histograms (quantiles are bucket upper bounds):")
        out.extend(hists)
    if not out:
        out.append("(empty snapshot)")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", help="metrics snapshot JSON (--metrics-out output)")
    p.add_argument(
        "--prometheus", action="store_true",
        help="re-emit as Prometheus text instead of the pretty table",
    )
    args = p.parse_args(argv)
    with open(args.path) as f:
        snapshot = json.load(f)
    if args.prometheus:
        from neuronx_distributed_inference_tpu.telemetry.metrics import (
            MetricsRegistry,
        )

        reg = MetricsRegistry()
        for name, fam in snapshot.items():
            for s in fam.get("samples", []):
                lnames = tuple(sorted(s.get("labels", {})))
                lvals = tuple(s["labels"][k] for k in lnames)
                if fam["type"] == "counter":
                    fam_obj = reg.counter(name, fam.get("help", ""), labels=lnames)
                    (fam_obj.child(lvals) if lnames else fam_obj).inc(s["value"])
                elif fam["type"] == "gauge":
                    fam_obj = reg.gauge(name, fam.get("help", ""), labels=lnames)
                    (fam_obj.child(lvals) if lnames else fam_obj).set(s["value"])
                # histograms can't round-trip exactly from cumulative counts;
                # the pretty table is their consumer
        print(reg.prometheus_text())
    else:
        print(render(snapshot))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
