#!/usr/bin/env python
"""Pretty-print a telemetry metrics snapshot (``--metrics-out`` JSON).

    python scripts/metrics_report.py metrics.json
    python scripts/metrics_report.py metrics.json --prometheus   # raw text

Stdlib-only on purpose: the snapshot format is the JSON side of the
exposition contract (docs/OBSERVABILITY.md), and this script is its
reference consumer — ``render()`` is imported by the test suite so the
format cannot drift silently.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _labels_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def _snapshot_quantile(sample: Dict, q: float):
    """Quantile of one snapshot histogram sample via the registry's OWN
    ``Histogram.quantile`` (bucket-resolution; reconstructed from the
    cumulative {le: count} map so script and exposition can never disagree
    on quantile semantics)."""
    from neuronx_distributed_inference_tpu.telemetry.metrics import Histogram

    items = list(sample["buckets"].items())
    bounds = tuple(
        float(le) for le, _ in items if le not in ("+Inf", "inf")
    )
    h = Histogram(bounds)
    prev = 0
    for i, (_le, cum) in enumerate(items):
        if i < len(h.counts):
            h.counts[i] = cum - prev
        prev = cum
    h.count = sample["count"]
    h.sum = sample["sum"]
    return h.quantile(q)


def _hist_line(indent: str, label: str, s: Dict) -> str:
    count = s["count"]
    mean = (s["sum"] / count) if count else 0.0
    qs = " ".join(
        f"p{int(q * 100)}<={_snapshot_quantile(s, q)}"
        for q in (0.50, 0.95, 0.99)
    )
    return (
        f"{indent}{label:<52} n={count:<8} sum={s['sum']:<12.6g} "
        f"mean={mean:<10.4g} {qs}"
    )


def render(snapshot: Dict) -> str:
    """One aligned table per metric kind. Families sort by name; a labelled
    family prints one header line with its per-label children indented
    beneath it (sorted by label string), so multi-label families read as a
    group instead of scattering in insertion order."""
    counters: List[str] = []
    gauges: List[str] = []
    hists: List[str] = []
    for name, fam in sorted(snapshot.items()):
        kind = fam.get("type")
        samples = fam.get("samples", [])
        labelled = [s for s in samples if s.get("labels")]
        plain = [s for s in samples if not s.get("labels")]
        sink = {"counter": counters, "gauge": gauges,
                "histogram": hists}.get(kind)
        if sink is None:
            continue
        for s in plain:
            if kind == "histogram":
                sink.append(_hist_line("  ", name, s))
            else:
                sink.append(f"  {name:<64} {s['value']:>14g}")
        if labelled:
            sink.append(f"  {name}")
            for s in sorted(
                labelled, key=lambda s: _labels_str(s["labels"])
            ):
                lab = _labels_str(s["labels"])
                if kind == "histogram":
                    sink.append(_hist_line("    ", lab, s))
                else:
                    sink.append(f"    {lab:<62} {s['value']:>14g}")
    out = []
    if counters:
        out.append("counters:")
        out.extend(counters)
    if gauges:
        out.append("gauges:")
        out.extend(gauges)
    if hists:
        out.append("histograms (quantiles are bucket upper bounds):")
        out.extend(hists)
    if not out:
        out.append("(empty snapshot)")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", help="metrics snapshot JSON (--metrics-out output)")
    p.add_argument(
        "--prometheus", action="store_true",
        help="re-emit as Prometheus text instead of the pretty table",
    )
    args = p.parse_args(argv)
    with open(args.path) as f:
        snapshot = json.load(f)
    if args.prometheus:
        from neuronx_distributed_inference_tpu.telemetry.metrics import (
            MetricsRegistry,
        )

        reg = MetricsRegistry()
        for name, fam in snapshot.items():
            for s in fam.get("samples", []):
                lnames = tuple(sorted(s.get("labels", {})))
                lvals = tuple(s["labels"][k] for k in lnames)
                if fam["type"] == "counter":
                    fam_obj = reg.counter(name, fam.get("help", ""), labels=lnames)
                    (fam_obj.child(lvals) if lnames else fam_obj).inc(s["value"])
                elif fam["type"] == "gauge":
                    fam_obj = reg.gauge(name, fam.get("help", ""), labels=lnames)
                    (fam_obj.child(lvals) if lnames else fam_obj).set(s["value"])
                # histograms can't round-trip exactly from cumulative counts;
                # the pretty table is their consumer
        print(reg.prometheus_text())
    else:
        print(render(snapshot))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
