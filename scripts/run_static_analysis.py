#!/usr/bin/env python
"""CI gate: run the static-analysis suites, exit non-zero on NEW findings.

Thin wrapper over ``python -m neuronx_distributed_inference_tpu.analysis``
so CI configs and humans share one entry point:

    JAX_PLATFORMS=cpu python scripts/run_static_analysis.py [--json]
    python scripts/run_static_analysis.py --suites lint,flags   # no tracing

The graph/shard/memory audits trace tiny tp-sharded models on a CPU mesh —
no accelerator required; the whole gate fits inside the tier-1 timeout.
After an INTENTIONAL contract change (a new collective, a resharded weight,
a footprint change, a new host-sync site), regenerate the committed
baselines with ``--write-baseline`` and review the printed unified diff
like code. ``bash scripts/ci_check.sh`` runs this gate plus the
static_analysis pytest subset as the one pre-PR command.
"""

import os
import sys

# force a CPU backend with virtual devices before jax initializes: the gate
# must give identical answers on a TPU host and in CPU-only CI
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the parser/dispatch is the SAME object the module CLI uses (analysis/cli.py)
# so --json/--suites/--write-baseline cannot drift between entry points
from neuronx_distributed_inference_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
